"""Failure detection → topology reaction: heartbeat expiry drives placement.

Reference behavior (SURVEY §5 failure detection / elastic recovery): the
reference watches service heartbeats (cluster/services/heartbeat) and
operators — or automation over the placement APIs — replace dead instances;
replicas stream the replacement's shards via peers bootstrap, and reads are
gated on shard state so an INITIALIZING replica never serves data it
doesn't have yet (topology readable-shard filtering).

``FailureDetector`` closes the loop in-process: it polls Services liveness
for one service, emits events on death/recovery, and (when given a spare
pool) runs placement.replace_instance through the PlacementService so the
cluster heals without an operator.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from .placement import PlacementService, replace_instance
from .services import Services


@dataclass
class FailureEvent:
    instance_id: str
    kind: str  # "dead" | "recovered" | "replaced"
    replacement_id: str | None = None
    at_monotonic: float = field(default_factory=time.monotonic)


class FailureDetector:
    """Polls heartbeat liveness; optionally auto-replaces dead instances.

    - ``grace``: how long past the heartbeat timeout before declaring death
      (debounces transient misses).
    - ``spares``: instance ids eligible to take over a dead instance's
      shards. Replacement consumes a spare; the placement change rides the
      PlacementService so every watcher (topology maps, nodes) converges.
    - ``on_event``: callback for observability / tests.
    """

    def __init__(
        self,
        services: Services,
        placement_svc: PlacementService,
        service_name: str = "m3db",
        grace: float = 5.0,
        spares: list[str] | None = None,
        on_event: Callable[[FailureEvent], None] | None = None,
        auto_replace: bool = True,
    ) -> None:
        self.services = services
        self.placement_svc = placement_svc
        self.service_name = service_name
        self.grace = grace
        self.spares = list(spares or [])
        self.on_event = on_event
        self.auto_replace = auto_replace
        self.events: list[FailureEvent] = []
        self._dead: set[str] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # --- liveness math ---

    def _live_ids(self) -> set[str]:
        return {i.id for i in self.services.instances(self.service_name, live_only=True)}

    def _known_ids(self) -> set[str]:
        return {
            i.id for i in self.services.instances(self.service_name, live_only=False)
        }

    def _emit(self, ev: FailureEvent) -> None:
        self.events.append(ev)
        if self.on_event is not None:
            self.on_event(ev)

    # --- one detection pass (callable directly from tests/clock drivers) ---

    def check(self, now: float | None = None) -> list[FailureEvent]:
        """Run one liveness pass; returns the events it produced."""
        now = time.monotonic() if now is None else now
        produced: list[FailureEvent] = []
        with self._lock:
            p = self.placement_svc.get()
            placed = set(p.instances) if p is not None else set()
            live = self._live_ids()
            timeout = self.services.heartbeat_timeout
            for inst in self.services.instances(self.service_name, live_only=False):
                age = now - inst.last_heartbeat
                if inst.id in self._dead:
                    if age < timeout:
                        self._dead.discard(inst.id)
                        ev = FailureEvent(inst.id, "recovered")
                        self._emit(ev)
                        produced.append(ev)
                    continue
                if age < timeout + self.grace or inst.id not in placed:
                    continue
                self._dead.add(inst.id)
                ev = FailureEvent(inst.id, "dead")
                self._emit(ev)
                produced.append(ev)
                if self.auto_replace and p is not None:
                    spare = next(
                        (s for s in self.spares if s not in placed and s not in self._dead),
                        None,
                    )
                    if spare is not None:
                        self.spares.remove(spare)
                        replace_instance(p, inst.id, spare)
                        self.placement_svc.set(p)
                        placed = set(p.instances)
                        rev = FailureEvent(inst.id, "replaced", replacement_id=spare)
                        self._emit(rev)
                        produced.append(rev)
        return produced

    # --- background driver ---

    def start(self, interval: float = 1.0) -> None:
        def loop() -> None:
            while not self._stop.wait(interval):
                try:
                    self.check()
                except Exception:
                    pass  # detector must never die to a transient error

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
