"""Failure detection → topology reaction: heartbeat expiry drives placement.

Reference behavior (SURVEY §5 failure detection / elastic recovery): the
reference watches service heartbeats (cluster/services/heartbeat) and
operators — or automation over the placement APIs — replace dead instances;
replicas stream the replacement's shards via peers bootstrap, and reads are
gated on shard state so an INITIALIZING replica never serves data it
doesn't have yet (topology readable-shard filtering).

``FailureDetector`` closes the loop in-process: it polls Services liveness
for one service, emits events on death/recovery, and (when given a spare
pool) runs placement.replace_instance through the PlacementService so the
cluster heals without an operator.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from ..utils.instrument import DEFAULT as METRICS
from .placement import PlacementService, replace_instance
from .services import Services

_LOG = logging.getLogger(__name__)


@dataclass
class FailureEvent:
    instance_id: str
    kind: str  # "dead" | "recovered" | "replaced"
    replacement_id: str | None = None
    at_monotonic: float = field(default_factory=time.monotonic)


class FailureDetector:
    """Polls heartbeat liveness; optionally auto-replaces dead instances.

    - ``grace``: how long past the heartbeat timeout before declaring death
      (debounces transient misses).
    - ``spares``: instance ids eligible to take over a dead instance's
      shards. Replacement consumes a spare; the placement change rides the
      PlacementService so every watcher (topology maps, nodes) converges.
    - ``on_event``: callback for observability / tests.
    """

    def __init__(
        self,
        services: Services,
        placement_svc: PlacementService,
        service_name: str = "m3db",
        grace: float = 5.0,
        spares: list[str] | None = None,
        on_event: Callable[[FailureEvent], None] | None = None,
        auto_replace: bool = True,
    ) -> None:
        self.services = services
        self.placement_svc = placement_svc
        self.service_name = service_name
        self.grace = grace
        self.spares = list(spares or [])
        self.on_event = on_event
        self.auto_replace = auto_replace
        self.events: list[FailureEvent] = []
        self._dead: set[str] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _emit(self, ev: FailureEvent) -> None:
        self.events.append(ev)
        if self.on_event is not None:
            self.on_event(ev)

    # --- one detection pass (callable directly from tests/clock drivers) ---

    def check(self, now: float | None = None) -> list[FailureEvent]:
        """Run one liveness pass; returns the events it produced."""
        now = self.services.clock() if now is None else now
        produced: list[FailureEvent] = []
        with self._lock:
            p, p_version = self.placement_svc.get_versioned()
            placed = set(p.instances) if p is not None else set()
            timeout = self.services.heartbeat_timeout
            # ONE discovery snapshot per pass (one bulk KV read) serves
            # liveness, the dead scan, and spare endpoint lookup
            all_insts = self.services.instances(self.service_name, live_only=False)
            alive = {
                i.id: i for i in all_insts if now - i.last_heartbeat < timeout
            }
            for inst in all_insts:
                age = now - inst.last_heartbeat
                if inst.id in self._dead:
                    if age < timeout:
                        self._dead.discard(inst.id)
                        ev = FailureEvent(inst.id, "recovered")
                        self._emit(ev)
                        produced.append(ev)
                    continue
                if age < timeout + self.grace or inst.id not in placed:
                    continue
                self._dead.add(inst.id)
                ev = FailureEvent(inst.id, "dead")
                self._emit(ev)
                produced.append(ev)
                if self.auto_replace and p is not None:
                    # a spare must be unplaced, LIVE, and advertised with an
                    # endpoint — promoting a crashed spare would wedge the
                    # cluster with unbootstrappable INITIALIZING shards
                    spare = next(
                        (
                            s
                            for s in self.spares
                            if s not in placed
                            and s not in self._dead
                            and s in alive
                            and alive[s].endpoint
                        ),
                        None,
                    )
                    if spare is not None:
                        spare_ep = alive[spare].endpoint
                        # CAS loop: a concurrent placement change (admin
                        # add/remove via the coordinator's threaded HTTP
                        # server) must not be clobbered by get→mutate→set.
                        # replace errors ("already in placement") terminate
                        # the loop — only CAS version conflicts retry.
                        replaced = False
                        while True:
                            try:
                                replace_instance(p, inst.id, spare)
                            except ValueError:
                                break  # another actor placed the spare
                            p.instances[spare].endpoint = spare_ep
                            try:
                                p_version = self.placement_svc.check_and_set(p, p_version)
                                replaced = True
                                break
                            except ValueError:
                                p, p_version = self.placement_svc.get_versioned()
                                if (
                                    p is None
                                    or inst.id not in p.instances
                                    or spare in p.instances
                                ):
                                    break  # someone else handled it
                        if replaced:
                            self.spares.remove(spare)
                            placed = set(p.instances)
                            rev = FailureEvent(inst.id, "replaced", replacement_id=spare)
                            self._emit(rev)
                            produced.append(rev)
                        else:
                            placed = set(p.instances) if p is not None else placed
        return produced

    # --- background driver ---

    def start(self, interval: float = 1.0) -> None:
        errors = METRICS.counter(
            "failure_detector_errors_total",
            "exceptions swallowed by the failure-detector poll loop",
        )

        def loop() -> None:
            logged = False
            while not self._stop.wait(interval):
                try:
                    self.check()
                except Exception:
                    # the detector must never die to a transient error, but
                    # a PERSISTENTLY failing detector silently leaves the
                    # cluster unhealed — count every swallow and log the
                    # first so it shows up in /metrics and the logs
                    errors.inc()
                    if not logged:
                        logged = True
                        _LOG.exception(
                            "failure detector poll failed (suppressing "
                            "further tracebacks; see "
                            "m3tpu_failure_detector_errors_total)"
                        )

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
