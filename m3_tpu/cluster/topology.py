"""Topology: shard→hosts map derived from placements + consistency levels.

Reference: /root/reference/src/dbnode/topology/ — dynamic topology watching
the placement (dynamic.go), shard→hosts map (map.go), consistency levels
(consistency_level.go: One/Majority/All + unstrict variants).
"""

from __future__ import annotations

import enum

from .placement import Placement, PlacementService, ShardState


class ConsistencyLevel(enum.Enum):
    ONE = "one"
    MAJORITY = "majority"
    # UNSTRICT_MAJORITY (consistency_level.go ReadConsistencyLevelUnstrictMajority):
    # PREFER a majority of replicas, but degrade a read to whatever
    # responded (at least one replica per touched shard) instead of
    # failing it — results are marked non-exhaustive by the session so the
    # caller knows it got the best-available view, not the quorum view.
    UNSTRICT_MAJORITY = "unstrict_majority"
    ALL = "all"

    def required(self, replicas: int) -> int:
        if self is ConsistencyLevel.ONE:
            return 1
        if self in (ConsistencyLevel.MAJORITY, ConsistencyLevel.UNSTRICT_MAJORITY):
            return replicas // 2 + 1
        return replicas

    @property
    def unstrict(self) -> bool:
        """Whether missing the required count degrades instead of failing
        (reads only; writes under an unstrict level still gate strictly)."""
        return self is ConsistencyLevel.UNSTRICT_MAJORITY


class TopologyMap:
    """topology/map.go: route shard → host list."""

    def __init__(self, placement: Placement) -> None:
        self.placement = placement

    @property
    def replicas(self) -> int:
        return self.placement.replica_factor

    def hosts_for_shard(self, shard: int, readable_only: bool = False) -> list[str]:
        return [
            i.id
            for i in self.placement.instances_for_shard(shard, readable_only=readable_only)
        ]

    def shard_state(self, instance_id: str, shard: int) -> ShardState | None:
        inst = self.placement.instances.get(instance_id)
        if inst is None:
            return None
        a = inst.shards.get(shard)
        return a.state if a else None


class DynamicTopology:
    """topology/dynamic.go: re-derive the map on placement changes."""

    def __init__(self, svc: PlacementService) -> None:
        self.svc = svc
        self.map: TopologyMap | None = None
        self._listeners = []
        svc.watch(self._on_placement)

    def _on_placement(self, p: Placement) -> None:
        self.map = TopologyMap(p)
        for fn in list(self._listeners):
            fn(self.map)

    def listen(self, fn) -> None:
        self._listeners.append(fn)
        if self.map is not None:
            fn(self.map)
