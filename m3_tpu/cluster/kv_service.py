"""Networked control plane: the KV store served over the wire protocol.

Reference: /root/reference/src/cluster/kv/etcd/store.go:54 — every process
in the reference reaches placements, namespaces, rules, topics, elections,
and runtime config through etcd. Here the same role is played by a KVStore
served over the framework's framed-RPC protocol (net/wire): one process
(standalone ``services.kvnode`` binary, or embedded in a dbnode seed node)
owns the store; every other process speaks to it through ``RemoteKVStore``,
which implements the exact KVStore interface — get/set/CAS/delete/keys plus
watches — so PlacementService, Services, TopicService, RuleStore, and
LeaderElection run unchanged against a remote control plane.

Watches are long-polls: the client asks "anything newer than version V?"
and the server blocks on the store's condition variable until there is
(etcd watch semantics without a push channel; at-least-once delivery, and
a watcher can never miss a final state because it always re-reads the
current version).
"""

from __future__ import annotations

import logging
import threading

from ..net.client import RpcClient
from ..net.server import RpcServer
from ..utils.instrument import DEFAULT as METRICS
from .kv import KVStore, VersionedValue

_LOG = logging.getLogger(__name__)

WATCH_POLL_TIMEOUT = 30.0


class KVService:
    """Dispatch table over a KVStore (the server side)."""

    def __init__(self, store: KVStore) -> None:
        self.store = store

    def handle(self, req: dict):
        op = req.get("op")
        fn = getattr(self, f"op_{op}", None)
        if fn is None:
            raise ValueError(f"unknown op {op!r}")
        return fn(req)

    def op_health(self, req):
        return {"role": "kv", "keys": len(self.store.keys())}

    def op_kv_get(self, req):
        vv = self.store.get(req["key"])
        return None if vv is None else {"version": vv.version, "value": vv.value}

    @staticmethod
    def _fence(req):
        f = req.get("fence")
        return tuple(f) if f else None

    def op_kv_set(self, req):
        return self.store.set(req["key"], req["value"], fence=self._fence(req))

    def op_kv_cas(self, req):
        return self.store.check_and_set(
            req["key"], req["expect"], req["value"], fence=self._fence(req)
        )

    # -- leases: expiry arbitrated on THIS server's clock (etcd lease role) --

    def op_kv_lease_acquire(self, req):
        return self.store.lease_acquire(req["key"], req["holder"], req["ttl"])

    def op_kv_lease_keepalive(self, req):
        return self.store.lease_keepalive(req["key"], req["holder"], req["token"])

    def op_kv_lease_release(self, req):
        return self.store.lease_release(req["key"], req["holder"], req["token"])

    def op_kv_lease_get(self, req):
        got = self.store.lease_get(req["key"])
        return None if got is None else list(got)

    def op_kv_lease_expire(self, req):
        self.store.lease_expire(req["key"])
        return True

    def op_kv_set_if_not_exists(self, req):
        return self.store.set_if_not_exists(req["key"], req["value"])

    def op_kv_delete(self, req):
        self.store.delete(req["key"])
        return True

    def op_kv_keys(self, req):
        return self.store.keys(req.get("prefix", ""))

    def op_kv_get_prefix(self, req):
        return [
            [k, vv.version, vv.value]
            for k, vv in self.store.get_prefix(req.get("prefix", "")).items()
        ]

    def op_kv_watch(self, req):
        """Long-poll: block until key's version > after, or timeout."""
        timeout = min(float(req.get("timeout", WATCH_POLL_TIMEOUT)), 120.0)
        vv = self.store.wait_for_version_gt(req["key"], req["after"], timeout)
        return None if vv is None else {"version": vv.version, "value": vv.value}


class KVServer(RpcServer):
    """TCP front end for a KVService."""

    def __init__(self, store: KVStore | None = None, host: str = "127.0.0.1", port: int = 0):
        self.store = store or KVStore()
        super().__init__(KVService(self.store), host=host, port=port, component="kv")


class RemoteKVStore:
    """Client-side kv.Store: same interface as KVStore, state lives in the
    KV server process(es). Watches run on a dedicated long-poll thread per
    key (its own connection, so data-plane calls never queue behind a poll).

    FAILOVER (etcd-client role): construct with one endpoint or several
    ("host:port,host:port,..."). Calls rotate to the next endpoint on
    connection failure, and follow NotLeaderError redirects to the raft
    leader for writes/leases — so a SIGKILLed KV replica (leader included)
    is transparent to placement watches, elections, and heartbeats.

    AT-LEAST-ONCE delivery: ``_call`` transparently re-sends an op when
    the connection drops before the response arrives, so an op that DID
    apply can be applied again. Idempotent ops (get/watch, check_and_set
    — the version guard makes the retry a no-op — and the lease ops) are
    retry-safe. The two non-idempotent writes are not: a ``set`` whose
    response was lost applies twice (version bumps twice, watches fire
    twice with the same value — harmless for last-writer-wins config
    keys, observable for version-sensitive callers), and a
    ``set_if_not_exists`` that actually succeeded retries into KeyError
    even though this caller created the key. Callers needing
    exactly-once semantics should route through check_and_set, or on
    KeyError read the key back and treat "exists with my value" as
    success."""

    FAILOVER_WINDOW = 20.0  # give a 3-node quorum time to elect + settle

    def __init__(self, host: str, port: int, timeout: float = 10.0) -> None:
        self.endpoints = [f"{host}:{port}"]
        self.timeout = timeout
        self._cur = 0
        self._lock = threading.Lock()
        self._clients: dict[str, RpcClient] = {}
        self._unsubs: list = []

    @classmethod
    def connect(cls, endpoint: str, **kwargs):
        """'host:port' or comma-separated 'host:port,host:port,...'."""
        eps = [e.strip() for e in endpoint.split(",") if e.strip()]
        host, port = eps[0].rsplit(":", 1)
        store = cls(host, int(port), **kwargs)
        store.endpoints = eps
        return store

    # compat: single-endpoint callers read .host/.port
    @property
    def host(self) -> str:
        return self.endpoints[self._cur].rsplit(":", 1)[0]

    @property
    def port(self) -> int:
        return int(self.endpoints[self._cur].rsplit(":", 1)[1])

    def _client_for(self, endpoint: str) -> RpcClient:
        with self._lock:
            c = self._clients.get(endpoint)
            if c is None:
                host, port = endpoint.rsplit(":", 1)
                c = RpcClient(host, int(port), pool_size=2, timeout=self.timeout)
                self._clients[endpoint] = c
            return c

    def _rotate(self, away_from: str) -> None:
        with self._lock:
            if self.endpoints[self._cur] == away_from:
                self._cur = (self._cur + 1) % len(self.endpoints)

    def _redirect(self, hint: str) -> None:
        if not hint or ":" not in hint:
            self._rotate(self.endpoints[self._cur])
            return
        with self._lock:
            if hint not in self.endpoints:
                self.endpoints.append(hint)
            self._cur = self.endpoints.index(hint)

    def _call(self, op: str, _timeout: float | None = None, **args):
        """Failover-aware call: rotates endpoints on connection errors and
        follows leader redirects until FAILOVER_WINDOW elapses."""
        import time as _time

        from ..net.client import RemoteError

        deadline = _time.monotonic() + self.FAILOVER_WINDOW
        last_exc: Exception | None = None
        while True:
            ep = self.endpoints[self._cur]
            try:
                return self._client_for(ep)._call(op, _timeout=_timeout, **args)
            except RemoteError as exc:
                if exc.etype == "NotLeaderError":
                    last_exc = exc
                    hint = str(exc).rsplit(" ", 1)[-1]
                    self._redirect(hint if hint != "NotLeaderError:" else "")
                elif exc.etype in ("RetryableError", "UnavailableError"):
                    # no leader yet / commit timeout, or the middleware's
                    # typed pre-dispatch rejection (expired deadline, load
                    # shed): nothing applied, safe to try again
                    last_exc = exc
                else:
                    raise
            except (ConnectionError, OSError, ValueError) as exc:
                last_exc = exc
                self._rotate(ep)
            if _time.monotonic() > deadline:
                raise last_exc
            _time.sleep(0.05)

    # -- kv.Store surface --

    def get(self, key: str) -> VersionedValue | None:
        r = self._call("kv_get", key=key)
        return None if r is None else VersionedValue(r["version"], r["value"])

    def set(self, key: str, value, fence=None) -> int:
        from .kv import FenceError
        from ..net.client import RemoteError

        try:
            return self._call(
                "kv_set", key=key, value=value, fence=list(fence) if fence else None
            )
        except RemoteError as exc:
            if exc.etype == "FenceError":
                raise FenceError(str(exc)) from exc
            raise

    def set_if_not_exists(self, key: str, value) -> int:
        # remote KeyError arrives as RemoteError(etype="KeyError"); re-raise
        # the local type so callers' except clauses work unchanged
        from ..net.client import RemoteError

        try:
            return self._call("kv_set_if_not_exists", key=key, value=value)
        except RemoteError as exc:
            if exc.etype == "KeyError":
                raise KeyError(str(exc)) from exc
            raise

    def check_and_set(self, key: str, expect_version: int, value, fence=None) -> int:
        from .kv import FenceError
        from ..net.client import RemoteError

        try:
            return self._call(
                "kv_cas", key=key, expect=expect_version, value=value,
                fence=list(fence) if fence else None,
            )
        except RemoteError as exc:
            if exc.etype == "ValueError":
                raise ValueError(str(exc)) from exc
            if exc.etype == "FenceError":
                raise FenceError(str(exc)) from exc
            raise

    def delete(self, key: str) -> None:
        self._call("kv_delete", key=key)

    def keys(self, prefix: str = "") -> list[str]:
        return self._call("kv_keys", prefix=prefix)

    def get_prefix(self, prefix: str = "") -> dict[str, VersionedValue]:
        return {
            k: VersionedValue(ver, val)
            for k, ver, val in self._call("kv_get_prefix", prefix=prefix)
        }

    # -- leases (arbitrated on the KV server's clock, never this host's) --

    def lease_acquire(self, key: str, holder: str, ttl: float) -> int:
        from .kv import LeaseHeld
        from ..net.client import RemoteError

        try:
            return self._call("kv_lease_acquire", key=key, holder=holder, ttl=ttl)
        except RemoteError as exc:
            if exc.etype == "LeaseHeld":
                # message: "LeaseHeld: lease held by <holder> for another <s>s"
                msg = str(exc)
                cur = msg.split("held by ", 1)[-1].split(" for another", 1)[0]
                raise LeaseHeld(cur, 0.0) from exc
            raise

    def lease_keepalive(self, key: str, holder: str, token: int) -> bool:
        return self._call("kv_lease_keepalive", key=key, holder=holder, token=token)

    def lease_release(self, key: str, holder: str, token: int) -> bool:
        return self._call("kv_lease_release", key=key, holder=holder, token=token)

    def lease_get(self, key: str) -> tuple[str, int] | None:
        got = self._call("kv_lease_get", key=key)
        return None if got is None else (got[0], got[1])

    def lease_expire(self, key: str) -> None:
        self._call("kv_lease_expire", key=key)

    def watch(self, key: str, fn) -> callable:
        """Fire fn(VersionedValue) on every version the poll observes,
        starting with the current value if the key exists. Returns an
        unsubscribe callable. Poll errors rotate to the next KV replica and
        retry — a watch survives both a KV server restart (backed stores
        reload their state) and a raft leader kill (followers serve watches
        from their applied state)."""
        stop = threading.Event()
        # unsub/close must be able to interrupt an in-flight long-poll: the
        # current poller is shared so they can close its socket from outside
        holder: list = [None]
        cb_logged = [False]

        def loop() -> None:
            last = 0
            cur = self._cur
            while not stop.is_set():
                try:
                    if holder[0] is None:
                        host, port = self.endpoints[cur].rsplit(":", 1)
                        holder[0] = RpcClient(
                            host, int(port), pool_size=1, timeout=self.timeout
                        )
                    # _retry=False: THIS loop owns failover (rotate to the
                    # next replica below) — a transparent same-endpoint
                    # retry would pay extra socket timeouts against a
                    # partitioned host before the rotation can happen
                    r = holder[0]._call(
                        "kv_watch",
                        key=key,
                        after=last,
                        timeout=WATCH_POLL_TIMEOUT,
                        _retry=False,
                        _timeout=WATCH_POLL_TIMEOUT + 5.0,
                    )
                except Exception:
                    if holder[0] is not None:
                        holder[0].close()
                    holder[0] = None
                    cur = (cur + 1) % len(self.endpoints)
                    stop.wait(0.2)
                    continue
                if stop.is_set():
                    break
                if r is None:
                    continue  # poll timeout; re-ask
                last = r["version"]
                try:
                    fn(VersionedValue(r["version"], r["value"]))
                except Exception:
                    # a watcher callback must not kill the poll loop — but
                    # a throwing callback is a real bug upstream, so count
                    # it and log the first occurrence per watch (M3L007)
                    METRICS.counter(
                        "kv_watch_callback_errors_total",
                        "exceptions raised by KV watch callbacks "
                        "(swallowed to keep the poll loop alive)",
                    ).inc()
                    if not cb_logged[0]:
                        cb_logged[0] = True
                        _LOG.exception(
                            "kv watch callback for %r failed (suppressing "
                            "further tracebacks; see "
                            "m3tpu_kv_watch_callback_errors_total)", key,
                        )
            if holder[0] is not None:
                holder[0].close()
                holder[0] = None

        t = threading.Thread(target=loop, daemon=True, name=f"kv-watch-{key}")
        t.start()

        def unsub() -> None:
            stop.set()
            if holder[0] is not None:
                holder[0].close()  # interrupt the in-flight long-poll
            with self._lock:
                if unsub in self._unsubs:
                    self._unsubs.remove(unsub)

        with self._lock:
            self._unsubs.append(unsub)
        return unsub

    def close(self) -> None:
        for unsub in list(self._unsubs):
            unsub()
        with self._lock:
            for c in self._clients.values():
                c.close()
            self._clients.clear()
