"""Networked control plane: the KV store served over the wire protocol.

Reference: /root/reference/src/cluster/kv/etcd/store.go:54 — every process
in the reference reaches placements, namespaces, rules, topics, elections,
and runtime config through etcd. Here the same role is played by a KVStore
served over the framework's framed-RPC protocol (net/wire): one process
(standalone ``services.kvnode`` binary, or embedded in a dbnode seed node)
owns the store; every other process speaks to it through ``RemoteKVStore``,
which implements the exact KVStore interface — get/set/CAS/delete/keys plus
watches — so PlacementService, Services, TopicService, RuleStore, and
LeaderElection run unchanged against a remote control plane.

Watches are long-polls: the client asks "anything newer than version V?"
and the server blocks on the store's condition variable until there is
(etcd watch semantics without a push channel; at-least-once delivery, and
a watcher can never miss a final state because it always re-reads the
current version).
"""

from __future__ import annotations

import threading

from ..net.client import RpcClient
from ..net.server import RpcServer
from .kv import KVStore, VersionedValue

WATCH_POLL_TIMEOUT = 30.0


class KVService:
    """Dispatch table over a KVStore (the server side)."""

    def __init__(self, store: KVStore) -> None:
        self.store = store

    def handle(self, req: dict):
        op = req.get("op")
        fn = getattr(self, f"op_{op}", None)
        if fn is None:
            raise ValueError(f"unknown op {op!r}")
        return fn(req)

    def op_health(self, req):
        return {"role": "kv", "keys": len(self.store.keys())}

    def op_kv_get(self, req):
        vv = self.store.get(req["key"])
        return None if vv is None else {"version": vv.version, "value": vv.value}

    def op_kv_set(self, req):
        return self.store.set(req["key"], req["value"])

    def op_kv_cas(self, req):
        return self.store.check_and_set(req["key"], req["expect"], req["value"])

    def op_kv_set_if_not_exists(self, req):
        return self.store.set_if_not_exists(req["key"], req["value"])

    def op_kv_delete(self, req):
        self.store.delete(req["key"])
        return True

    def op_kv_keys(self, req):
        return self.store.keys(req.get("prefix", ""))

    def op_kv_get_prefix(self, req):
        return [
            [k, vv.version, vv.value]
            for k, vv in self.store.get_prefix(req.get("prefix", "")).items()
        ]

    def op_kv_watch(self, req):
        """Long-poll: block until key's version > after, or timeout."""
        timeout = min(float(req.get("timeout", WATCH_POLL_TIMEOUT)), 120.0)
        vv = self.store.wait_for_version_gt(req["key"], req["after"], timeout)
        return None if vv is None else {"version": vv.version, "value": vv.value}


class KVServer(RpcServer):
    """TCP front end for a KVService."""

    def __init__(self, store: KVStore | None = None, host: str = "127.0.0.1", port: int = 0):
        self.store = store or KVStore()
        super().__init__(KVService(self.store), host=host, port=port)


class RemoteKVStore(RpcClient):
    """Client-side kv.Store: same interface as KVStore, state lives in the
    KV server process. Watches run on a dedicated long-poll thread per key
    (its own connection, so data-plane calls never queue behind a poll)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0) -> None:
        super().__init__(host, port, pool_size=2, timeout=timeout)
        self._watch_stops: list[threading.Event] = []

    # -- kv.Store surface --

    def get(self, key: str) -> VersionedValue | None:
        r = self._call("kv_get", key=key)
        return None if r is None else VersionedValue(r["version"], r["value"])

    def set(self, key: str, value) -> int:
        return self._call("kv_set", key=key, value=value)

    def set_if_not_exists(self, key: str, value) -> int:
        # remote KeyError arrives as RemoteError(etype="KeyError"); re-raise
        # the local type so callers' except clauses work unchanged
        from .kv import KVStore as _  # noqa: F401  (doc anchor)
        from ..net.client import RemoteError

        try:
            return self._call("kv_set_if_not_exists", key=key, value=value)
        except RemoteError as exc:
            if exc.etype == "KeyError":
                raise KeyError(str(exc)) from exc
            raise

    def check_and_set(self, key: str, expect_version: int, value) -> int:
        from ..net.client import RemoteError

        try:
            return self._call("kv_cas", key=key, expect=expect_version, value=value)
        except RemoteError as exc:
            if exc.etype == "ValueError":
                raise ValueError(str(exc)) from exc
            raise

    def delete(self, key: str) -> None:
        self._call("kv_delete", key=key)

    def keys(self, prefix: str = "") -> list[str]:
        return self._call("kv_keys", prefix=prefix)

    def get_prefix(self, prefix: str = "") -> dict[str, VersionedValue]:
        return {
            k: VersionedValue(ver, val)
            for k, ver, val in self._call("kv_get_prefix", prefix=prefix)
        }

    def watch(self, key: str, fn) -> callable:
        """Fire fn(VersionedValue) on every version the poll observes,
        starting with the current value if the key exists. Returns an
        unsubscribe callable. Poll errors back off and retry — a watch
        survives a KV server restart (backed stores reload their state)."""
        stop = threading.Event()
        self._watch_stops.append(stop)
        poller = RpcClient(self.host, self.port, pool_size=1, timeout=self.timeout)

        def loop() -> None:
            last = 0
            while not stop.is_set():
                try:
                    r = poller._call(
                        "kv_watch",
                        key=key,
                        after=last,
                        timeout=WATCH_POLL_TIMEOUT,
                        _timeout=WATCH_POLL_TIMEOUT + 5.0,
                    )
                except Exception:
                    stop.wait(0.2)
                    continue
                if stop.is_set():
                    break
                if r is None:
                    continue  # poll timeout; re-ask
                last = r["version"]
                try:
                    fn(VersionedValue(r["version"], r["value"]))
                except Exception:
                    pass  # a watcher callback must not kill the poll loop

        t = threading.Thread(target=loop, daemon=True, name=f"kv-watch-{key}")
        t.start()

        def unsub() -> None:
            stop.set()
            poller.close()

        return unsub

    def close(self) -> None:
        for stop in self._watch_stops:
            stop.set()
        super().close()
