"""Placements: instance/shard assignment with shard states.

Reference: /root/reference/src/cluster/placement/ — placement.Placement model
(types.go), sharded placement algorithm (algo/sharded.go: balanced initial
assignment, add/remove instance moves the minimum number of shards), shard
states Initializing/Available/Leaving (src/cluster/shard/) gating reads, and
placement storage in KV (placement/storage).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from .kv import KVStore


class ShardState(enum.IntEnum):
    INITIALIZING = 0
    AVAILABLE = 1
    LEAVING = 2


@dataclass
class ShardAssignment:
    shard: int
    state: ShardState = ShardState.INITIALIZING
    source_instance: str | None = None  # where to stream from while initializing


@dataclass
class Instance:
    id: str
    endpoint: str = ""
    isolation_group: str = ""
    weight: int = 1
    shards: dict[int, ShardAssignment] = field(default_factory=dict)


@dataclass
class Placement:
    instances: dict[str, Instance] = field(default_factory=dict)
    num_shards: int = 0
    replica_factor: int = 1
    version: int = 0

    def instances_for_shard(self, shard: int, readable_only: bool = False) -> list[Instance]:
        out = []
        for inst in self.instances.values():
            a = inst.shards.get(shard)
            if a is None:
                continue
            if readable_only and a.state == ShardState.INITIALIZING:
                continue
            out.append(inst)
        return out

    def mark_all_available(self) -> None:
        for inst in self.instances.values():
            for a in inst.shards.values():
                a.state = ShardState.AVAILABLE

    def to_dict(self) -> dict:
        return {
            "numShards": self.num_shards,
            "replicaFactor": self.replica_factor,
            "instances": {
                iid: {
                    "endpoint": inst.endpoint,
                    "isolationGroup": inst.isolation_group,
                    "weight": inst.weight,
                    "shards": {
                        str(s): {"state": int(a.state), "source": a.source_instance}
                        for s, a in inst.shards.items()
                    },
                }
                for iid, inst in self.instances.items()
            },
        }

    @staticmethod
    def from_dict(d: dict) -> "Placement":
        p = Placement(num_shards=d["numShards"], replica_factor=d["replicaFactor"])
        for iid, v in d["instances"].items():
            inst = Instance(iid, v["endpoint"], v["isolationGroup"], v["weight"])
            for s, a in v["shards"].items():
                inst.shards[int(s)] = ShardAssignment(
                    int(s), ShardState(a["state"]), a.get("source")
                )
            p.instances[iid] = inst
        return p


def build_initial_placement(
    instance_ids: list[str], num_shards: int, replica_factor: int
) -> Placement:
    """algo/sharded.go initial placement: round-robin replicas across
    instances, no two replicas of a shard on the same instance."""
    if replica_factor > len(instance_ids):
        raise ValueError("replica factor exceeds instance count")
    p = Placement(num_shards=num_shards, replica_factor=replica_factor)
    for iid in instance_ids:
        p.instances[iid] = Instance(iid)
    n = len(instance_ids)
    for s in range(num_shards):
        for r in range(replica_factor):
            iid = instance_ids[(s + r) % n]
            p.instances[iid].shards[s] = ShardAssignment(s, ShardState.AVAILABLE)
    return p


def add_instance(p: Placement, new_id: str) -> Placement:
    """algo/sharded.go AddInstance: steal shards from the most-loaded
    instances; stolen shards start INITIALIZING with a source to stream from."""
    if new_id in p.instances:
        raise ValueError(f"instance {new_id} already in placement")
    target = p.num_shards * p.replica_factor // (len(p.instances) + 1)
    new_inst = Instance(new_id)
    p.instances[new_id] = new_inst
    while len(new_inst.shards) < target:
        donor = max(
            (i for i in p.instances.values() if i.id != new_id),
            key=lambda i: len(i.shards),
        )
        movable = [
            s
            for s, a in donor.shards.items()
            if a.state == ShardState.AVAILABLE and s not in new_inst.shards
        ]
        if not movable:
            break
        s = movable[0]
        del donor.shards[s]
        new_inst.shards[s] = ShardAssignment(
            s, ShardState.INITIALIZING, source_instance=donor.id
        )
    p.version += 1
    return p


def remove_instance(p: Placement, iid: str) -> Placement:
    """algo/sharded.go RemoveInstance: redistribute its shards to the
    least-loaded remaining instances."""
    gone = p.instances.pop(iid)
    for s, a in gone.shards.items():
        candidates = sorted(
            (i for i in p.instances.values() if s not in i.shards),
            key=lambda i: len(i.shards),
        )
        if not candidates:
            continue
        dst = candidates[0]
        dst.shards[s] = ShardAssignment(s, ShardState.INITIALIZING, source_instance=None)
    p.version += 1
    return p


def replace_instance(p: Placement, old_id: str, new_id: str) -> Placement:
    """algo/sharded.go ReplaceInstances: the new instance inherits ALL of the
    old one's shards as INITIALIZING (streaming from the leaving instance);
    the old instance's shards turn LEAVING and are removed once the new
    instance marks them available (mark_shards_available)."""
    if new_id in p.instances:
        raise ValueError(f"instance {new_id} already in placement")
    old = p.instances[old_id]
    new_inst = Instance(new_id, isolation_group=old.isolation_group, weight=old.weight)
    for s, a in list(old.shards.items()):
        if a.state == ShardState.INITIALIZING:
            # the old instance never had this shard's data: nothing to hand
            # off or read from — drop it there and inherit the ORIGINAL
            # stream source (keeping it LEAVING would leave a phantom
            # readable replica that mark_shards_available can never clear)
            new_inst.shards[s] = ShardAssignment(
                s, ShardState.INITIALIZING, source_instance=a.source_instance
            )
            del old.shards[s]
        else:
            new_inst.shards[s] = ShardAssignment(
                s, ShardState.INITIALIZING, source_instance=old_id
            )
            a.state = ShardState.LEAVING
    if not old.shards:
        del p.instances[old_id]
    p.instances[new_id] = new_inst
    p.version += 1
    return p


def mark_shards_available(p: Placement, iid: str, shards=None) -> Placement:
    """MarkShardsAvailable (placement/service): INITIALIZING → AVAILABLE on
    ``iid``; the matching LEAVING shard on the source instance is dropped
    (and an emptied leaving instance is removed from the placement)."""
    inst = p.instances[iid]
    ids = list(inst.shards) if shards is None else shards
    emptied_sources: set[str] = set()
    for s in ids:
        a = inst.shards.get(s)
        if a is None or a.state != ShardState.INITIALIZING:
            continue
        if a.source_instance:
            src = p.instances.get(a.source_instance)
            if src is not None:
                sa = src.shards.get(s)
                if sa is not None and sa.state == ShardState.LEAVING:
                    del src.shards[s]
                    if not src.shards:
                        emptied_sources.add(src.id)
        a.state = ShardState.AVAILABLE
        a.source_instance = None
    # only sources THIS call emptied leave the placement — an instance that
    # legitimately owns zero shards stays
    for gone in emptied_sources:
        if not p.instances[gone].shards:
            del p.instances[gone]
    p.version += 1
    return p


def build_mirrored_placement(
    groups: list[list[str]], num_shards: int
) -> Placement:
    """algo/mirrored.go: instances within a group mirror each other — every
    member owns the IDENTICAL shard set (the aggregator's leader/follower
    pairs are placed this way); replica factor = group size."""
    sizes = {len(g) for g in groups}
    if len(sizes) != 1:
        raise ValueError("mirrored placement requires equal-size groups")
    rf = sizes.pop()
    if rf == 0 or not groups:
        raise ValueError("mirrored placement requires non-empty groups")
    p = Placement(num_shards=num_shards, replica_factor=rf)
    for gi, group in enumerate(groups):
        for iid in group:
            inst = Instance(iid, isolation_group=f"group{gi}")
            # contiguous shard range per group, remainder to the last group
            lo = num_shards * gi // len(groups)
            hi = num_shards * (gi + 1) // len(groups)
            for s in range(lo, hi):
                inst.shards[s] = ShardAssignment(s, ShardState.AVAILABLE)
            p.instances[iid] = inst
    return p


class PlacementService:
    """placement.Service: placements stored + versioned in KV."""

    KEY = "_placement/{name}"

    def __init__(self, kv: KVStore, name: str = "default") -> None:
        self.kv = kv
        self.key = self.KEY.format(name=name)

    def get(self) -> Placement | None:
        vv = self.kv.get(self.key)
        return Placement.from_dict(vv.value) if vv else None

    def get_versioned(self) -> tuple[Placement | None, int]:
        """Placement plus its KV version, for CAS mutation loops."""
        vv = self.kv.get(self.key)
        return (Placement.from_dict(vv.value), vv.version) if vv else (None, 0)

    def set(self, p: Placement) -> int:
        return self.kv.set(self.key, p.to_dict())

    def check_and_set(self, p: Placement, expect_version: int) -> int:
        return self.kv.check_and_set(self.key, expect_version, p.to_dict())

    def watch(self, fn) -> callable:
        return self.kv.watch(self.key, lambda vv: fn(Placement.from_dict(vv.value)))
