"""Replicated control plane: raft-lite consensus over the framed RPC wire.

Reference: the reference's control plane is an etcd raft quorum — embedded
seed nodes inside dbnodes (/root/reference/src/dbnode/server/server.go:266-324)
with every cluster subsystem reaching it through kv.Store
(/root/reference/src/cluster/kv/etcd/store.go:54). This module plays raft's
role for the tpu framework's kvnode: three (or any odd number of) kvnode
processes form a quorum; placements, namespaces, rules, topics, elections,
leases and flush times survive the loss of any minority, including the
leader, with no committed write lost.

Design (raft, simplified where the paper allows):
- Leader election with randomized timeouts, term monotonicity, and the
  log-up-to-date voting restriction (§5.2, §5.4.1) — so only a replica
  holding every committed entry can win.
- Log replication with consistency check + conflict truncation (§5.3);
  followers return their last index as a hint for fast next_index backup.
- Commit rule: an entry is committed once a majority holds it AND it is
  from the leader's current term (§5.4.2); a no-op entry is appended at
  election so prior-term entries commit promptly.
- Snapshot + log compaction (§7): the state machine (a cluster.kv.KVStore)
  dumps/restores wholesale; laggards receive an install-snapshot RPC.
- Persistence: term/vote in meta.json, entries appended to log.jsonl
  (flushed per append), snapshots in snap.json — a restarted node rejoins
  with its log intact and re-learns commit from the leader.

Determinism: every state-machine command carries the proposing leader's
wall clock (``now``) IN the log entry, so lease-expiry arbitration and
fence checks replay identically on every replica — replicas never read
their own clocks while applying.

Client semantics: writes and lease ops are leader-only (followers raise
NotLeaderError with the leader's endpoint as a redirect hint); reads and
long-poll watches are served from any replica's applied state (followers
lag by at most one replication round; watch correctness only needs version
monotonicity, which applied order guarantees).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time

from ..net.client import RpcClient
from .kv import KVStore


class NotLeaderError(RuntimeError):
    """Raised by a non-leader replica for a write; message is the leader's
    endpoint hint (may be empty if unknown)."""


class RetryableError(RuntimeError):
    """Transient condition (no leader yet / commit timed out / leadership
    lost mid-commit): the client should retry, possibly elsewhere."""


MAX_ENTRIES_PER_APPEND = 1024


class RaftNode:
    """One consensus replica wrapping a KVStore state machine."""

    def __init__(
        self,
        node_id: str,
        store: KVStore | None = None,
        data_dir: str | None = None,
        heartbeat_interval: float = 0.1,
        election_timeout: tuple[float, float] = (0.4, 0.8),
        compact_threshold: int = 20000,
        clock=time.time,
    ) -> None:
        self.node_id = node_id
        self.store = store or KVStore()
        self.clock = clock
        self.heartbeat_interval = heartbeat_interval
        self.election_timeout = election_timeout
        self.compact_threshold = compact_threshold

        self._mu = threading.RLock()
        self._commit_cv = threading.Condition(self._mu)
        self._prop_cv = threading.Condition(self._mu)

        # persistent raft state
        self.term = 0
        self.voted_for: str | None = None
        self.log: list[dict] = []  # {"term": int, "cmd": {...}}
        # snapshot point: the persisted/installed snapshot is the state
        # machine EXACTLY at snap_index (dumped at last_applied when taken)
        self.snap_index = 0
        self.snap_term = 0
        # log floor: index of the entry just below log[0]. Kept <= snap_index
        # so a tail of already-applied entries can be retained for follower
        # catch-up by append — WITHOUT mislabelling the snapshot (state@X
        # must never be paired with index<X, or re-applied tail entries
        # double-apply and replicas diverge).
        self.log_floor = 0
        self.floor_term = 0

        # volatile
        self.role = "follower"
        self.leader_id: str | None = None
        self.leader_endpoint: str = ""
        self.commit_index = 0
        self.last_applied = 0
        self._last_hb = time.monotonic()
        self._timeout = random.uniform(*election_timeout)
        # read-barrier lease: (term, monotonic stamp) of the last quorum
        # leadership confirmation (no-op commit); reads within one
        # heartbeat interval of it skip re-confirming
        self._barrier_term = -1
        self._barrier_at = 0.0

        # leader volatile
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}

        # membership: id -> endpoint for ALL members (incl. self)
        self.members: dict[str, str] = {}
        self.endpoint = ""

        self._waiters: dict[int, _Waiter] = {}
        self._peer_clients: dict[str, RpcClient] = {}
        self._clients_lock = threading.Lock()  # _peer_clients is touched
        # by replicators/vote askers OUTSIDE _mu
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._log_fh = None

        self._dir = os.path.join(data_dir, "raft") if data_dir else None
        if self._dir:
            os.makedirs(self._dir, exist_ok=True)
            self._recover()

    # ---------- persistence ----------

    def _meta_path(self):
        return os.path.join(self._dir, "meta.json")

    def _log_path(self):
        return os.path.join(self._dir, "log.jsonl")

    def _snap_path(self):
        return os.path.join(self._dir, "snap.json")

    def _persist_meta(self) -> None:
        if not self._dir:
            return
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"term": self.term, "voted_for": self.voted_for,
                 "members": self.members, "endpoint": self.endpoint,
                 "log_floor": self.log_floor, "floor_term": self.floor_term},
                f,
            )
        os.replace(tmp, self._meta_path())

    def _append_log_disk(self, entries: list[dict], first: int) -> None:
        """``first`` is the raft index of entries[0]; every on-disk record
        carries its index so recovery can realign after a crash between a
        snapshot persist and the log rewrite."""
        if not self._dir:
            return
        if self._log_fh is None:
            self._log_fh = open(self._log_path(), "a")
        for off, e in enumerate(entries):
            self._log_fh.write(json.dumps({"i": first + off, **e}) + "\n")
        self._log_fh.flush()

    def _rewrite_log_disk(self) -> None:
        """Full rewrite (conflict truncation or compaction)."""
        if not self._dir:
            return
        if self._log_fh is not None:
            self._log_fh.close()
            self._log_fh = None
        tmp = self._log_path() + ".tmp"
        with open(tmp, "w") as f:
            for off, e in enumerate(self.log):
                f.write(json.dumps({"i": self.first_index + off, **e}) + "\n")
        os.replace(tmp, self._log_path())

    def _persist_snap(self) -> None:
        if not self._dir:
            return
        tmp = self._snap_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"index": self.snap_index, "term": self.snap_term,
                 "state": self.store.dump()},
                f,
            )
        os.replace(tmp, self._snap_path())

    def _recover(self) -> None:
        if os.path.exists(self._snap_path()):
            with open(self._snap_path()) as f:
                snap = json.load(f)
            self.snap_index = snap["index"]
            self.snap_term = snap["term"]
            self.store.restore(snap["state"])
        self.log_floor, self.floor_term = self.snap_index, self.snap_term
        if os.path.exists(self._meta_path()):
            with open(self._meta_path()) as f:
                meta = json.load(f)
            self.term = meta["term"]
            self.voted_for = meta["voted_for"]
            members = meta.get("members") or {}
            if members:
                self.members = members
                self.endpoint = meta.get("endpoint", "")
            floor = meta.get("log_floor")
            if floor is not None and floor <= self.snap_index:
                self.log_floor = floor
                self.floor_term = meta.get("floor_term", 0)
        if os.path.exists(self._log_path()):
            # realign by each record's index: drop entries the snapshot
            # already covers, stop at any gap (torn write / crash between
            # snapshot persist and log rewrite)
            self.log = []
            expect = self.first_index
            with open(self._log_path()) as f:
                for ln in f:
                    if not ln.strip():
                        continue
                    try:
                        rec = json.loads(ln)
                    except ValueError:
                        break  # torn tail
                    idx = rec.pop("i", expect)
                    if idx < expect:
                        continue  # covered by the snapshot / duplicate
                    if idx > expect:
                        break  # gap: discard the rest
                    self.log.append(rec)
                    expect += 1
        self.commit_index = self.last_applied = self.snap_index

    # ---------- log indexing (1-based; log starts above log_floor) ----------

    @property
    def first_index(self) -> int:
        return self.log_floor + 1

    @property
    def last_log_index(self) -> int:
        return self.log_floor + len(self.log)

    def _term_at(self, index: int) -> int:
        if index == self.log_floor:
            return self.floor_term
        return self.log[index - self.first_index]["term"]

    def _entries_from(self, index: int) -> list[dict]:
        return self.log[index - self.first_index:]

    # ---------- membership / lifecycle ----------

    def configure(self, members: dict[str, str], self_endpoint: str | None = None) -> None:
        """Set the member map (id -> endpoint, including this node) and
        start timers/replicators. Idempotent; persisted so a restarted node
        rejoins on its own."""
        with self._mu:
            self.members = dict(members)
            self.endpoint = self_endpoint or self.members.get(self.node_id, "")
            self.members[self.node_id] = self.endpoint
            # peer endpoints may have changed (restart on a fresh port)
            with self._clients_lock:
                stale, self._peer_clients = list(self._peer_clients.values()), {}
            for c in stale:
                c.close()
            self._persist_meta()
            started = bool(self._threads)
        if not started:
            for t in (
                threading.Thread(
                    target=self._ticker, daemon=True, name=f"raft-tick-{self.node_id}"
                ),
                threading.Thread(
                    target=self._applier, daemon=True, name=f"raft-apply-{self.node_id}"
                ),
            ):
                self._threads.append(t)
                t.start()
        with self._mu:
            # always (re)ensure replicators — reconfiguration may add members
            self._ensure_replicators()
            if len(self.members) == 1 and self.role != "leader":
                self._become_leader()

    def _ensure_replicators(self) -> None:
        for pid in self.members:
            if pid == self.node_id:
                continue
            name = f"raft-repl-{self.node_id}->{pid}"
            if any(t.name == name for t in self._threads):
                continue
            t = threading.Thread(
                target=self._replicator, args=(pid,), daemon=True, name=name
            )
            self._threads.append(t)
            t.start()

    def stop(self) -> None:
        self._stop.set()
        with self._mu:
            self.role = "follower"  # a stopped node must not accept proposals
            self._fail_waiters(RetryableError("node stopping"))
            self._prop_cv.notify_all()
            self._commit_cv.notify_all()
        with self._clients_lock:
            clients, self._peer_clients = list(self._peer_clients.values()), {}
        for c in clients:
            c.close()
        if self._log_fh is not None:
            self._log_fh.close()
            self._log_fh = None

    def _client(self, pid: str) -> RpcClient:
        with self._clients_lock:
            c = self._peer_clients.get(pid)
            if c is None:
                host, port = self.members[pid].rsplit(":", 1)
                c = RpcClient(host, int(port), pool_size=1, timeout=2.0)
                self._peer_clients[pid] = c
            return c

    @property
    def quorum(self) -> int:
        return len(self.members) // 2 + 1

    # ---------- roles ----------

    def _step_down(self, term: int) -> None:
        """Caller holds the lock."""
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._persist_meta()
        if self.role == "leader":
            # entries past commit may or may not survive; clients retry
            self._fail_waiters(RetryableError("leadership lost"))
        self.role = "follower"
        self._timeout = random.uniform(*self.election_timeout)

    def _fail_waiters(self, exc: Exception) -> None:
        for w in self._waiters.values():
            w.error = exc
            w.event.set()
        self._waiters.clear()

    def _become_leader(self) -> None:
        """Caller holds the lock."""
        self.role = "leader"
        self.leader_id = self.node_id
        self.leader_endpoint = self.endpoint
        for pid in self.members:
            if pid != self.node_id:
                self.next_index[pid] = self.last_log_index + 1
                self.match_index[pid] = 0
        # no-op from the new term so earlier entries commit (§5.4.2)
        self._append_local({"op": "noop"})
        self._advance_commit()
        self._prop_cv.notify_all()

    def _ticker(self) -> None:
        while not self._stop.wait(0.03):
            with self._mu:
                if self.role == "leader" or len(self.members) <= 1:
                    continue
                if time.monotonic() - self._last_hb < self._timeout:
                    continue
                # become candidate
                self.term += 1
                self.voted_for = self.node_id
                self.role = "candidate"
                self._persist_meta()
                term = self.term
                last_i, last_t = self.last_log_index, self._term_at(self.last_log_index)
                self._last_hb = time.monotonic()
                self._timeout = random.uniform(*self.election_timeout)
                peers = [p for p in self.members if p != self.node_id]
            votes = [1]  # self
            done = threading.Event()
            lock = threading.Lock()

            def ask(pid: str) -> None:
                try:
                    r = self._client(pid)._call(
                        "raft_vote", term=term, candidate=self.node_id,
                        last_log_index=last_i, last_log_term=last_t,
                        _timeout=0.5,
                    )
                except Exception:
                    return
                with self._mu:
                    if r["term"] > self.term:
                        self._step_down(r["term"])
                        done.set()
                        return
                if r.get("granted"):
                    with lock:
                        votes[0] += 1
                        if votes[0] >= self.quorum:
                            done.set()

            askers = [threading.Thread(target=ask, args=(p,), daemon=True) for p in peers]
            for t in askers:
                t.start()
            done.wait(self.election_timeout[0])
            with self._mu:
                if self.role == "candidate" and self.term == term and votes[0] >= self.quorum:
                    self._become_leader()

    # ---------- replication (leader side) ----------

    def _replicator(self, pid: str) -> None:
        backoff = 0.0
        while not self._stop.is_set():
            with self._mu:
                if self.role == "leader" and self.next_index.get(pid, 1) <= self.last_log_index:
                    pass  # work to do now
                else:
                    self._prop_cv.wait(self.heartbeat_interval)
                if self.role != "leader" or self._stop.is_set():
                    continue
                term = self.term
                ni = self.next_index.get(pid, self.last_log_index + 1)
                if ni <= self.log_floor:
                    # install-snapshot: the dump reflects the state machine
                    # at last_applied, so it MUST be labelled last_applied —
                    # labelling it lower would re-apply retained tail
                    # entries on the follower and diverge replicas
                    snap = {
                        "term": term, "leader": self.node_id,
                        "leader_endpoint": self.endpoint,
                        "snap_index": self.last_applied,
                        "snap_term": self._term_at(self.last_applied),
                        "state": self.store.dump(),
                    }
                    req = ("raft_snapshot", snap)
                else:
                    prev = ni - 1
                    entries = self.log[ni - self.first_index:][:MAX_ENTRIES_PER_APPEND]
                    req = (
                        "raft_append",
                        {
                            "term": term, "leader": self.node_id,
                            "leader_endpoint": self.endpoint,
                            "prev_index": prev, "prev_term": self._term_at(prev),
                            "entries": entries, "leader_commit": self.commit_index,
                        },
                    )
            if backoff:
                if self._stop.wait(backoff):
                    return
            try:
                r = self._client(pid)._call(req[0], _timeout=2.0, **req[1])
                backoff = 0.0
            except Exception:
                backoff = min((backoff or 0.05) * 2, 1.0)
                continue
            with self._mu:
                if r["term"] > self.term:
                    self._step_down(r["term"])
                    continue
                if self.role != "leader" or self.term != term:
                    continue
                if req[0] == "raft_snapshot":
                    sent = req[1]["snap_index"]
                    self.next_index[pid] = sent + 1
                    self.match_index[pid] = max(self.match_index.get(pid, 0), sent)
                    self._advance_commit()
                    continue
                if r.get("ok"):
                    match = req[1]["prev_index"] + len(req[1]["entries"])
                    self.match_index[pid] = max(self.match_index.get(pid, 0), match)
                    self.next_index[pid] = self.match_index[pid] + 1
                    self._advance_commit()
                else:
                    # fast backup using the follower's hint
                    hint = r.get("hint", req[1]["prev_index"] - 1)
                    self.next_index[pid] = max(1, min(req[1]["prev_index"], hint + 1))

    def _advance_commit(self) -> None:
        """Caller holds the lock (leader)."""
        for n in range(self.last_log_index, self.commit_index, -1):
            if self._term_at(n) != self.term:
                break  # only current-term entries commit by counting (§5.4.2)
            count = 1 + sum(1 for p, m in self.match_index.items() if m >= n)
            if count >= self.quorum:
                self.commit_index = n
                self._commit_cv.notify_all()
                break

    # ---------- RPC handlers (follower side) ----------

    def handle_vote(self, req: dict) -> dict:
        with self._mu:
            if req["term"] < self.term:
                return {"term": self.term, "granted": False}
            if req["term"] > self.term:
                self._step_down(req["term"])
            mine = (self._term_at(self.last_log_index), self.last_log_index)
            theirs = (req["last_log_term"], req["last_log_index"])
            if self.voted_for in (None, req["candidate"]) and theirs >= mine:
                self.voted_for = req["candidate"]
                self._persist_meta()
                self._last_hb = time.monotonic()
                return {"term": self.term, "granted": True}
            return {"term": self.term, "granted": False}

    def handle_append(self, req: dict) -> dict:
        with self._mu:
            if req["term"] < self.term:
                return {"term": self.term, "ok": False}
            if req["term"] > self.term or self.role != "follower":
                self._step_down(req["term"])
            self.leader_id = req["leader"]
            self.leader_endpoint = req.get("leader_endpoint", "")
            self._last_hb = time.monotonic()

            prev = req["prev_index"]
            if prev > self.last_log_index:
                return {"term": self.term, "ok": False, "hint": self.last_log_index}
            if prev >= self.first_index:
                if self._term_at(prev) != req["prev_term"]:
                    # conflict: drop the tail from prev on
                    self.log = self.log[: prev - self.first_index]
                    self._rewrite_log_disk()
                    self._fail_waiters(RetryableError("log truncated"))
                    return {
                        "term": self.term, "ok": False,
                        "hint": max(self.log_floor, prev - 1),
                    }
            elif prev == self.log_floor and prev > 0:
                if self._term_at(prev) != req["prev_term"]:
                    # entries at/below the floor are committed by definition
                    # (floor <= snap_index <= last_applied): a term mismatch
                    # here means local state is corrupt — fail loudly rather
                    # than truncate committed entries
                    raise RuntimeError(
                        f"{self.node_id}: prev_term mismatch at log floor "
                        f"{prev} (have {self._term_at(prev)}, leader says "
                        f"{req['prev_term']}) — committed state diverged"
                    )
            elif prev < self.log_floor:
                # entries at/below our floor are committed by definition
                # (floor <= snap_index <= last_applied); skip the overlap
                skip = self.log_floor - prev
                req = {**req, "entries": req["entries"][skip:], "prev_index": self.log_floor}
                prev = self.log_floor

            new = req["entries"]
            if new:
                # truncate any conflicting suffix, then append the rest
                idx = prev + 1
                keep = []
                for e in new:
                    if idx <= self.last_log_index:
                        if self._term_at(idx) != e["term"]:
                            self.log = self.log[: idx - self.first_index]
                            self._rewrite_log_disk()
                            self._fail_waiters(RetryableError("log truncated"))
                            keep.append(e)
                    else:
                        keep.append(e)
                    idx += 1
                if keep:
                    first = self.last_log_index + 1
                    self.log.extend(keep)
                    self._append_log_disk(keep, first)
            match = prev + len(new)
            if req["leader_commit"] > self.commit_index:
                self.commit_index = min(req["leader_commit"], self.last_log_index)
                self._commit_cv.notify_all()
            return {"term": self.term, "ok": True, "match": match}

    def handle_snapshot(self, req: dict) -> dict:
        with self._mu:
            if req["term"] < self.term:
                return {"term": self.term, "ok": False}
            if req["term"] > self.term or self.role != "follower":
                self._step_down(req["term"])
            self.leader_id = req["leader"]
            self.leader_endpoint = req.get("leader_endpoint", "")
            self._last_hb = time.monotonic()
            if req["snap_index"] <= self.snap_index:
                return {"term": self.term, "ok": True}
            self.store.restore(req["state"])
            self.snap_index = req["snap_index"]
            self.snap_term = req["snap_term"]
            self.log = []
            self.log_floor, self.floor_term = self.snap_index, self.snap_term
            self.commit_index = max(self.commit_index, self.snap_index)
            self.last_applied = self.snap_index
            self._persist_snap()
            self._persist_meta()
            self._rewrite_log_disk()
            return {"term": self.term, "ok": True}

    # ---------- propose / apply ----------

    def _append_local(self, cmd: dict) -> int:
        """Caller holds the lock (leader)."""
        entry = {"term": self.term, "cmd": cmd}
        self.log.append(entry)
        self._append_log_disk([entry], self.last_log_index)
        return self.last_log_index

    def propose(self, cmd: dict, timeout: float = 10.0):
        """Replicate one state-machine command; returns its apply result
        (or raises its apply error). Leader-only."""
        with self._mu:
            if self.role != "leader":
                raise NotLeaderError(self.leader_endpoint or "")
            cmd = {**cmd, "now": self.clock()}
            index = self._append_local(cmd)
            waiter = _Waiter(self.term)
            self._waiters[index] = waiter
            if len(self.members) == 1:
                self.commit_index = index
                self._commit_cv.notify_all()
            self._prop_cv.notify_all()
        if not waiter.event.wait(timeout):
            with self._mu:
                self._waiters.pop(index, None)
            raise RetryableError("commit timeout")
        if waiter.error is not None:
            raise waiter.error
        return waiter.result

    def read_barrier(self, timeout: float = 5.0) -> None:
        """Linearizable-read barrier (the no-op-commit flavor of etcd's
        ReadIndex): confirm this node is STILL the quorum's leader — a
        deposed leader in a partition minority must not serve reads from
        its stale applied state — then wait ``last_applied >=
        commit_index`` so the state machine reflects everything the read
        must observe.

        Confirmation commits a no-op through the log (its quorum
        replication IS the leadership proof, and propose() returns only
        after the entry applies, which also satisfies the apply barrier).
        A lease bounds the cost: within one heartbeat interval of a
        confirmation in the same term only the apply-catch-up wait runs —
        the standard lease-read trade-off (a stale read window exists only
        under clock malfunction within that interval).

        Raises NotLeaderError when not leader, RetryableError on timeout.
        """
        with self._mu:
            if self.role != "leader":
                raise NotLeaderError(self.leader_endpoint or "")
            commit = self.commit_index
            single = len(self.members) <= 1
            fresh = (
                self._barrier_term == self.term
                and time.monotonic() - self._barrier_at < self.heartbeat_interval
            )
        if not single and not fresh:
            self.propose({"op": "noop"}, timeout=timeout)
            with self._mu:
                self._barrier_term = self.term
                self._barrier_at = time.monotonic()
            return  # the noop applied => last_applied >= its index > commit
        deadline = time.monotonic() + timeout
        while True:
            with self._mu:
                if self.last_applied >= commit:
                    return
                if self.role != "leader":
                    raise NotLeaderError(self.leader_endpoint or "")
            if time.monotonic() >= deadline:
                raise RetryableError("read barrier apply-wait timeout")
            time.sleep(0.002)

    def _applier(self) -> None:
        # each entry is applied UNDER the raft lock so a concurrent
        # install-snapshot or conflict truncation can never interleave with
        # an apply (it would regress last_applied / index into a cleared log)
        while not self._stop.is_set():
            with self._mu:
                while self.last_applied >= self.commit_index and not self._stop.is_set():
                    self._commit_cv.wait(0.5)
                if self._stop.is_set():
                    return
                index = self.last_applied + 1
                if index < self.first_index:
                    # a snapshot install moved the floor past us; the
                    # snapshot state already covers through snap_index
                    self.last_applied = max(self.last_applied, self.snap_index)
                    continue
                entry = self.log[index - self.first_index]
                result, error = self._apply_cmd(entry["cmd"])
                self.last_applied = index
                w = self._waiters.pop(index, None)
                if w is not None:
                    if entry["term"] == w.term:
                        w.result, w.error = result, error
                    else:
                        w.error = RetryableError("entry superseded")
                    w.event.set()
            self._maybe_compact()

    def _apply_cmd(self, cmd: dict):
        """Apply one command to the KVStore. Deterministic: the only clock
        is cmd['now'], stamped by the proposing leader."""
        op = cmd["op"]
        now = cmd.get("now", 0.0)
        fence = tuple(cmd["fence"]) if cmd.get("fence") else None
        s = self.store
        try:
            if op == "noop":
                return None, None
            if op == "set":
                return s.set(cmd["key"], cmd["value"], fence=fence, now=now), None
            if op == "snei":
                return s.set_if_not_exists(cmd["key"], cmd["value"]), None
            if op == "cas":
                return (
                    s.check_and_set(
                        cmd["key"], cmd["expect"], cmd["value"], fence=fence, now=now
                    ),
                    None,
                )
            if op == "delete":
                s.delete(cmd["key"])
                return True, None
            if op == "lease_acquire":
                return s.lease_acquire(cmd["key"], cmd["holder"], cmd["ttl"], now=now), None
            if op == "lease_keepalive":
                return (
                    s.lease_keepalive(cmd["key"], cmd["holder"], cmd["token"], now=now),
                    None,
                )
            if op == "lease_release":
                return s.lease_release(cmd["key"], cmd["holder"], cmd["token"]), None
            if op == "lease_expire":
                s.lease_expire(cmd["key"])
                return True, None
            return None, ValueError(f"unknown raft cmd {op!r}")
        except Exception as exc:  # deterministic domain errors (CAS, fence, lease)
            return None, exc

    def _maybe_compact(self) -> None:
        with self._mu:
            if len(self.log) < self.compact_threshold:
                return
            # keep a tail of applied entries so followers a few heartbeats
            # behind catch up by append, not by full install-snapshot
            tail = min(MAX_ENTRIES_PER_APPEND, max(16, self.compact_threshold // 4))
            keep_from = self.last_applied - tail
            if keep_from <= self.log_floor:
                return
            # the snapshot is the state machine AT last_applied (dump below);
            # the log floor moves only to keep_from, retaining the tail —
            # the two indices are distinct on purpose (see __init__ notes)
            self.snap_index = self.last_applied
            self.snap_term = self._term_at(self.last_applied)
            self.floor_term = self._term_at(keep_from)
            self.log = self.log[keep_from - self.first_index + 1:]
            self.log_floor = keep_from
            self._persist_snap()
            self._persist_meta()
            self._rewrite_log_disk()

    # ---------- introspection ----------

    def status(self) -> dict:
        with self._mu:
            return {
                "id": self.node_id,
                "role": self.role,
                "term": self.term,
                "leader": self.leader_id,
                "leader_endpoint": self.leader_endpoint,
                "commit": self.commit_index,
                "applied": self.last_applied,
                "last_log_index": self.last_log_index,
                "members": dict(self.members),
            }

    @property
    def is_leader(self) -> bool:
        return self.role == "leader"


class _Waiter:
    __slots__ = ("event", "result", "error", "term")

    def __init__(self, term: int) -> None:
        self.event = threading.Event()
        self.result = None
        self.error: Exception | None = None
        self.term = term


class RaftKVService:
    """KV service front end over a RaftNode: plain reads are
    LINEARIZABLE — leader-only behind a read barrier (quorum leadership
    confirmation + apply catch-up, read_barrier above) so they are never
    stale across partitions; watches serve from any replica's applied
    state (version-gated, eventually consistent by design); writes +
    leases propose through the log (leader only; followers redirect with
    NotLeaderError). Peer raft RPCs ride the same dispatch table — one
    server port per kvnode."""

    def __init__(self, node: RaftNode) -> None:
        from .kv_service import KVService

        self.node = node
        self.store = node.store
        self._reads = KVService(node.store)

    # linearizable-by-default reads (etcd's default): plain reads redirect
    # to the leader AND pass a read barrier there (RaftNode.read_barrier:
    # quorum leadership confirmation + last_applied catch-up) — a deposed
    # leader in a partition minority redirects or times out instead of
    # serving stale state. Watches are version-gated long-polls and stay
    # on any replica (they deliver eventually and never regress).
    LEADER_READS = frozenset({"kv_get", "kv_keys", "kv_get_prefix"})

    def handle(self, req: dict):
        op = req.get("op")
        if op in self.LEADER_READS:
            if not self.node.is_leader:
                raise NotLeaderError(self.node.leader_endpoint or "")
            self.node.read_barrier()
        fn = getattr(self, f"op_{op}", None)
        if fn is not None:
            return fn(req)
        # watches, health fall through to the plain KV service
        return self._reads.handle(req)

    # -- raft peer RPCs --

    def op_raft_vote(self, req):
        return self.node.handle_vote(req)

    def op_raft_append(self, req):
        return self.node.handle_append(req)

    def op_raft_snapshot(self, req):
        return self.node.handle_snapshot(req)

    def op_raft_configure(self, req):
        self.node.configure(req["members"], req.get("self_endpoint"))
        return True

    def op_raft_status(self, req):
        return self.node.status()

    # -- writes: replicate through the log --

    def _propose(self, cmd: dict):
        return self.node.propose(cmd)

    def op_kv_set(self, req):
        return self._propose(
            {"op": "set", "key": req["key"], "value": req["value"],
             "fence": req.get("fence")}
        )

    def op_kv_set_if_not_exists(self, req):
        return self._propose({"op": "snei", "key": req["key"], "value": req["value"]})

    def op_kv_cas(self, req):
        return self._propose(
            {"op": "cas", "key": req["key"], "expect": req["expect"],
             "value": req["value"], "fence": req.get("fence")}
        )

    def op_kv_delete(self, req):
        return self._propose({"op": "delete", "key": req["key"]})

    # -- leases: leader-only, server-clock arbitration rides the log --

    def op_kv_lease_acquire(self, req):
        return self._propose(
            {"op": "lease_acquire", "key": req["key"], "holder": req["holder"],
             "ttl": req["ttl"]}
        )

    def op_kv_lease_keepalive(self, req):
        return self._propose(
            {"op": "lease_keepalive", "key": req["key"], "holder": req["holder"],
             "token": req["token"]}
        )

    def op_kv_lease_release(self, req):
        return self._propose(
            {"op": "lease_release", "key": req["key"], "holder": req["holder"],
             "token": req["token"]}
        )

    def op_kv_lease_expire(self, req):
        return self._propose({"op": "lease_expire", "key": req["key"]})

    def op_kv_lease_get(self, req):
        # expiry is judged on the LEADER's clock against the freshest state
        if not self.node.is_leader:
            raise NotLeaderError(self.node.leader_endpoint or "")
        got = self.store.lease_get(req["key"])
        return None if got is None else list(got)
