"""Dynamic namespace registry in the control-plane KV.

Reference: /root/reference/src/dbnode/namespace/dynamic.go — namespaces are
a single versioned registry value in etcd; every dbnode watches it and
applies adds/updates live (server.go KV-watch reconfig), and the
coordinator's database-create admin API writes it. Same shape here: one KV
key holding {name → options}, CAS-mutated, watched by nodes.
"""

from __future__ import annotations

KEY = "_namespaces"


class NamespaceExistsError(ValueError):
    """Create with options differing from the registered namespace."""


class NamespaceRegistry:
    """Versioned registry of namespace options (namespace/dynamic.go)."""

    def __init__(self, kv) -> None:
        self.kv = kv

    def get_all(self) -> dict[str, dict]:
        vv = self.kv.get(KEY)
        return dict(vv.value) if vv and vv.value else {}

    def add(
        self,
        name: str,
        retention_nanos: int,
        block_size_nanos: int,
        cold_writes_enabled: bool = True,
    ) -> None:
        """CAS insert. A namespace that already exists with DIFFERENT
        options raises NamespaceExistsError from INSIDE the retry loop —
        checking before calling would be a TOCTOU race between concurrent
        admin calls, and silently overwriting would diverge replicas that
        already created the namespace from the old record."""
        rec = {
            "retention_nanos": int(retention_nanos),
            "block_size_nanos": int(block_size_nanos),
            "cold_writes_enabled": bool(cold_writes_enabled),
        }
        while True:
            vv = self.kv.get(KEY)
            cur = dict(vv.value) if vv and vv.value else {}
            existing = cur.get(name)
            if existing == rec:
                return
            if existing is not None:
                raise NamespaceExistsError(
                    f"namespace {name} already exists with different options"
                )
            cur[name] = rec
            try:
                if vv is None:
                    self.kv.set_if_not_exists(KEY, cur)
                else:
                    self.kv.check_and_set(KEY, vv.version, cur)
                return
            except (ValueError, KeyError):
                continue  # raced; re-read and retry

    def watch(self, fn):
        """fn(registry_dict) on every version; fires with current value."""
        return self.kv.watch(KEY, lambda vv: fn(dict(vv.value or {})))
