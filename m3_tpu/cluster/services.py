"""Service discovery, heartbeats, and leader election over the KV store.

Reference: /root/reference/src/cluster/services/ — advertise+watch instances
(services.Services), heartbeat (services/heartbeat/etcd), leader election
(services/leader wrapping etcd concurrency primitives; the aggregator's
election_mgr.go campaigns through it, and the coordinator's in-process
downsampler uses a local stub leader_local.go — which this also covers).

All state lives in the KV store — point Services at a RemoteKVStore and
advertisement/heartbeats/liveness work across real processes, exactly as
the reference's etcd heartbeat store does. Heartbeats are wall-clock
timestamps written into the instance record; liveness is derived by age.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from .kv import KVStore


@dataclass
class ServiceInstance:
    id: str
    endpoint: str
    zone: str = "embedded"
    last_heartbeat: float = field(default_factory=time.time)


class Services:
    """Advertise + watch + heartbeat liveness (KV-backed)."""

    PREFIX = "_services/"

    def __init__(self, kv: KVStore, heartbeat_timeout: float = 10.0, clock=time.time) -> None:
        self.kv = kv
        self.heartbeat_timeout = heartbeat_timeout
        self.clock = clock
        # instances advertised BY THIS PROCESS: id → (service, endpoint, zone)
        # so heartbeat() is a single KV set, not get+set
        self._own: dict[tuple[str, str], tuple[str, str]] = {}

    def _key(self, service: str, instance_id: str) -> str:
        return f"{self.PREFIX}{service}/{instance_id}"

    def advertise(self, service: str, instance: ServiceInstance) -> None:
        self._own[(service, instance.id)] = (instance.endpoint, instance.zone)
        self.kv.set(
            self._key(service, instance.id),
            {"endpoint": instance.endpoint, "zone": instance.zone, "hb": self.clock()},
        )

    def heartbeat(self, service: str, instance_id: str) -> None:
        own = self._own.get((service, instance_id))
        if own is not None:
            endpoint, zone = own
        else:
            vv = self.kv.get(self._key(service, instance_id))
            if vv is None:
                return
            endpoint, zone = vv.value["endpoint"], vv.value.get("zone", "embedded")
        self.kv.set(
            self._key(service, instance_id),
            {"endpoint": endpoint, "zone": zone, "hb": self.clock()},
        )

    def unadvertise(self, service: str, instance_id: str) -> None:
        self._own.pop((service, instance_id), None)
        self.kv.delete(self._key(service, instance_id))

    def instances(self, service: str, live_only: bool = True) -> list[ServiceInstance]:
        now = self.clock()
        prefix = f"{self.PREFIX}{service}/"
        out = []
        # one bulk range read (one RPC on the networked store)
        for key, vv in self.kv.get_prefix(prefix).items():
            rec = vv.value
            inst = ServiceInstance(
                key[len(prefix):], rec["endpoint"], rec.get("zone", "embedded"),
                rec.get("hb", 0.0),
            )
            if live_only and now - inst.last_heartbeat >= self.heartbeat_timeout:
                continue
            out.append(inst)
        return sorted(out, key=lambda i: i.id)

    # test hook: age an instance's heartbeat (fault injection without sleeping)
    def _backdate(self, service: str, instance_id: str, secs: float) -> None:
        key = self._key(service, instance_id)
        vv = self.kv.get(key)
        if vv is not None:
            rec = dict(vv.value)
            rec["hb"] = rec.get("hb", 0.0) - secs
            self.kv.set(key, rec)


class LeaderElection:
    """Per-electionID campaign/resign/leader (services/leader/election).

    Leadership is a SERVER-ARBITRATED lease (cluster/kv.py lease ops —
    etcd-session semantics): expiry is judged on the KV server's clock, so
    cross-process client clock skew (or a suspended leader resuming) can
    never yield two live leaders; a SIGKILLed leader expires on its own.
    Every distinct acquisition carries a strictly-increasing FENCING TOKEN
    (``fence``) that leaders attach to their flush/state writes — the store
    rejects writes fenced with a superseded token, so a deposed leader's
    late writes are harmless. ``expire()`` force-expires for tests (the
    fake-clusterservices pattern)."""

    def __init__(self, kv: KVStore, election_id: str, lease_secs: float = 10.0) -> None:
        self.kv = kv
        self.key = f"_election/{election_id}"
        self.lease_secs = lease_secs
        self._tokens: dict[str, int] = {}

    def campaign(self, candidate: str) -> bool:
        from .kv import LeaseHeld

        try:
            self._tokens[candidate] = self.kv.lease_acquire(
                self.key, candidate, self.lease_secs
            )
            return True
        except LeaseHeld:
            return False

    def fence(self, candidate: str):
        """(lease_key, holder, token) for fenced writes; None if this
        candidate never won."""
        token = self._tokens.get(candidate)
        return None if token is None else (self.key, candidate, token)

    def leader(self) -> str | None:
        got = self.kv.lease_get(self.key)
        return got[0] if got else None

    def resign(self, candidate: str) -> None:
        token = self._tokens.pop(candidate, None)
        if token is not None:
            self.kv.lease_release(self.key, candidate, token)

    def expire(self) -> None:
        """Simulate session expiry (leader process died)."""
        self.kv.lease_expire(self.key)

    def watch(self, fn) -> callable:
        def relay(vv) -> None:
            v = vv.value
            fn(v.get("holder") if isinstance(v, dict) else None)

        return self.kv.watch(self.key, relay)
