"""Service discovery, heartbeats, and leader election over the KV store.

Reference: /root/reference/src/cluster/services/ — advertise+watch instances
(services.Services), heartbeat (services/heartbeat/etcd), leader election
(services/leader wrapping etcd concurrency primitives; the aggregator's
election_mgr.go campaigns through it, and the coordinator's in-process
downsampler uses a local stub leader_local.go — which this also covers).

All state lives in the KV store — point Services at a RemoteKVStore and
advertisement/heartbeats/liveness work across real processes, exactly as
the reference's etcd heartbeat store does. Heartbeats are wall-clock
timestamps written into the instance record; liveness is derived by age.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from .kv import KVStore


@dataclass
class ServiceInstance:
    id: str
    endpoint: str
    zone: str = "embedded"
    last_heartbeat: float = field(default_factory=time.time)


class Services:
    """Advertise + watch + heartbeat liveness (KV-backed)."""

    PREFIX = "_services/"

    def __init__(self, kv: KVStore, heartbeat_timeout: float = 10.0, clock=time.time) -> None:
        self.kv = kv
        self.heartbeat_timeout = heartbeat_timeout
        self.clock = clock
        # instances advertised BY THIS PROCESS: id → (service, endpoint, zone)
        # so heartbeat() is a single KV set, not get+set
        self._own: dict[tuple[str, str], tuple[str, str]] = {}

    def _key(self, service: str, instance_id: str) -> str:
        return f"{self.PREFIX}{service}/{instance_id}"

    def advertise(self, service: str, instance: ServiceInstance) -> None:
        self._own[(service, instance.id)] = (instance.endpoint, instance.zone)
        self.kv.set(
            self._key(service, instance.id),
            {"endpoint": instance.endpoint, "zone": instance.zone, "hb": self.clock()},
        )

    def heartbeat(self, service: str, instance_id: str) -> None:
        own = self._own.get((service, instance_id))
        if own is not None:
            endpoint, zone = own
        else:
            vv = self.kv.get(self._key(service, instance_id))
            if vv is None:
                return
            endpoint, zone = vv.value["endpoint"], vv.value.get("zone", "embedded")
        self.kv.set(
            self._key(service, instance_id),
            {"endpoint": endpoint, "zone": zone, "hb": self.clock()},
        )

    def unadvertise(self, service: str, instance_id: str) -> None:
        self._own.pop((service, instance_id), None)
        self.kv.delete(self._key(service, instance_id))

    def instances(self, service: str, live_only: bool = True) -> list[ServiceInstance]:
        now = self.clock()
        prefix = f"{self.PREFIX}{service}/"
        out = []
        # one bulk range read (one RPC on the networked store)
        for key, vv in self.kv.get_prefix(prefix).items():
            rec = vv.value
            inst = ServiceInstance(
                key[len(prefix):], rec["endpoint"], rec.get("zone", "embedded"),
                rec.get("hb", 0.0),
            )
            if live_only and now - inst.last_heartbeat >= self.heartbeat_timeout:
                continue
            out.append(inst)
        return sorted(out, key=lambda i: i.id)

    # test hook: age an instance's heartbeat (fault injection without sleeping)
    def _backdate(self, service: str, instance_id: str, secs: float) -> None:
        key = self._key(service, instance_id)
        vv = self.kv.get(key)
        if vv is not None:
            rec = dict(vv.value)
            rec["hb"] = rec.get("hb", 0.0) - secs
            self.kv.set(key, rec)


class LeaderElection:
    """Per-electionID campaign/resign/leader (services/leader/election).

    LEASED leadership over a CAS'd KV key (etcd-session semantics without
    etcd): the leader's record carries a wall-clock lease timestamp it
    refreshes on every campaign() call; a challenger may CAS-take the key
    once the lease has aged past ``lease_secs`` — so a SIGKILLed leader
    expires on its own across real processes. ``expire()`` force-expires
    for tests (the fake-clusterservices pattern)."""

    def __init__(
        self, kv: KVStore, election_id: str, lease_secs: float = 10.0, clock=time.time
    ) -> None:
        self.kv = kv
        self.key = f"_election/{election_id}"
        self.lease_secs = lease_secs
        self.clock = clock

    @staticmethod
    def _id_of(value) -> str | None:
        if value is None:
            return None
        return value["id"] if isinstance(value, dict) else value

    def campaign(self, candidate: str) -> bool:
        vv = self.kv.get(self.key)
        now = self.clock()
        cur = vv.value if vv else None
        cur_id = self._id_of(cur)
        if cur_id == candidate:
            # refresh the lease; a successful CAS proves we still hold it
            try:
                self.kv.check_and_set(
                    self.key, vv.version, {"id": candidate, "t": now}
                )
                return True
            except ValueError:
                return self.leader() == candidate
        if cur_id is not None:
            # a record with no parseable lease (legacy string value, missing
            # 't') must count as EXPIRED — treating it as fresh would block
            # takeover from a dead leader forever
            held_at = cur.get("t", 0) if isinstance(cur, dict) else 0
            if now - held_at <= self.lease_secs:
                return False  # live leader
            # lease expired: fall through to take over
        try:
            self.kv.check_and_set(
                self.key, vv.version if vv else 0, {"id": candidate, "t": now}
            )
            return True
        except (ValueError, KeyError):
            return self.leader() == candidate

    def leader(self) -> str | None:
        vv = self.kv.get(self.key)
        return self._id_of(vv.value) if vv else None

    def resign(self, candidate: str) -> None:
        vv = self.kv.get(self.key)
        if vv and self._id_of(vv.value) == candidate:
            self.kv.check_and_set(self.key, vv.version, None)

    def expire(self) -> None:
        """Simulate session expiry (leader process died)."""
        vv = self.kv.get(self.key)
        if vv:
            self.kv.check_and_set(self.key, vv.version, None)

    def watch(self, fn) -> callable:
        return self.kv.watch(self.key, lambda vv: fn(self._id_of(vv.value)))
