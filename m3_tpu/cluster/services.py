"""Service discovery, heartbeats, and leader election over the KV store.

Reference: /root/reference/src/cluster/services/ — advertise+watch instances
(services.Services), heartbeat (services/heartbeat/etcd), leader election
(services/leader wrapping etcd concurrency primitives; the aggregator's
election_mgr.go campaigns through it, and the coordinator's in-process
downsampler uses a local stub leader_local.go — which this also covers).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from .kv import KVStore


@dataclass
class ServiceInstance:
    id: str
    endpoint: str
    zone: str = "embedded"
    last_heartbeat: float = field(default_factory=time.monotonic)


class Services:
    """Advertise + watch + heartbeat liveness."""

    def __init__(self, kv: KVStore, heartbeat_timeout: float = 10.0) -> None:
        self.kv = kv
        self.heartbeat_timeout = heartbeat_timeout
        self._lock = threading.RLock()
        self._instances: dict[str, dict[str, ServiceInstance]] = {}

    def advertise(self, service: str, instance: ServiceInstance) -> None:
        with self._lock:
            self._instances.setdefault(service, {})[instance.id] = instance
        self.kv.set(f"_services/{service}/{instance.id}", instance.endpoint)

    def heartbeat(self, service: str, instance_id: str) -> None:
        with self._lock:
            inst = self._instances.get(service, {}).get(instance_id)
            if inst:
                inst.last_heartbeat = time.monotonic()

    def unadvertise(self, service: str, instance_id: str) -> None:
        with self._lock:
            self._instances.get(service, {}).pop(instance_id, None)
        self.kv.delete(f"_services/{service}/{instance_id}")

    def instances(self, service: str, live_only: bool = True) -> list[ServiceInstance]:
        now = time.monotonic()
        with self._lock:
            out = list(self._instances.get(service, {}).values())
        if live_only:
            out = [i for i in out if now - i.last_heartbeat < self.heartbeat_timeout]
        return sorted(out, key=lambda i: i.id)


class LeaderElection:
    """Per-electionID campaign/resign/leader (services/leader/election).

    CAS on a KV key; leadership is lost when the leader resigns or its
    session is explicitly expired (the fake-clusterservices pattern the
    reference's integration tests rely on)."""

    def __init__(self, kv: KVStore, election_id: str) -> None:
        self.kv = kv
        self.key = f"_election/{election_id}"

    def campaign(self, candidate: str) -> bool:
        vv = self.kv.get(self.key)
        if vv is None or vv.value is None:
            try:
                self.kv.check_and_set(self.key, vv.version if vv else 0, candidate)
                return True
            except (ValueError, KeyError):
                return self.leader() == candidate
        return vv.value == candidate

    def leader(self) -> str | None:
        vv = self.kv.get(self.key)
        return vv.value if vv else None

    def resign(self, candidate: str) -> None:
        vv = self.kv.get(self.key)
        if vv and vv.value == candidate:
            self.kv.check_and_set(self.key, vv.version, None)

    def expire(self) -> None:
        """Simulate session expiry (leader process died)."""
        vv = self.kv.get(self.key)
        if vv:
            self.kv.check_and_set(self.key, vv.version, None)

    def watch(self, fn) -> callable:
        return self.kv.watch(self.key, lambda vv: fn(vv.value))
