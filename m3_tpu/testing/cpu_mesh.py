"""Force JAX onto a virtual N-device CPU host mesh.

Single source of truth for the env hygiene needed in this image: a
sitecustomize may pre-register a TPU PJRT plugin (gated on
PALLAS_AXON_POOL_IPS) and force ``jax_platforms`` to it, so both an env-var
scrub (for child processes, before interpreter start) and a post-import
``jax.config.update`` (for an already-running interpreter) are required.
Used by tests/conftest.py and __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import os

# Env vars that can override or re-route the platform choice.
_PLATFORM_SELECTORS = (
    "PJRT_DEVICE",
    "JAX_PLATFORM_NAME",
    "TPU_LIBRARY_PATH",
    "PALLAS_AXON_POOL_IPS",
)


def cpu_mesh_env(n_devices: int, base: dict | None = None) -> dict:
    """A copy of ``base`` (default os.environ) forcing an n-device CPU mesh.

    For spawning child processes: takes effect before any jax import there.
    """
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    for k in _PLATFORM_SELECTORS:
        env.pop(k, None)
    return env


# Snapshot of the platform-selecting env vars as they were before
# force_cpu_mesh scrubbed them; lets child processes (e.g. the real-TPU smoke
# test) restore the original accelerator environment.
_SAVED_ENV: dict[str, str | None] = {}


def original_env(base: dict | None = None) -> dict:
    """A copy of ``base`` (default os.environ) with any force_cpu_mesh
    scrubbing undone — suitable for spawning a child that should see the
    machine's real accelerator."""
    env = dict(os.environ if base is None else base)
    for k, v in _SAVED_ENV.items():
        if v is None:
            env.pop(k, None)
        else:
            env[k] = v
    return env


def force_cpu_mesh(n_devices: int) -> None:
    """Force the CURRENT process onto an n-device CPU mesh.

    Must run before jax creates any backend. Applies both the env scrub and
    the config override (the latter wins over a plugin's sitecustomize-time
    platform selection).
    """
    for k in ("JAX_PLATFORMS", "XLA_FLAGS", *_PLATFORM_SELECTORS):
        _SAVED_ENV.setdefault(k, os.environ.get(k))
    os.environ.update(
        {k: v for k, v in cpu_mesh_env(n_devices).items() if k in ("JAX_PLATFORMS", "XLA_FLAGS")}
    )
    for k in _PLATFORM_SELECTORS:
        os.environ.pop(k, None)

    import jax

    jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    assert devs[0].platform == "cpu" and len(devs) >= n_devices, devs
