"""m3em-role environment manager: remote process-lifecycle agents.

Reference: /root/reference/src/m3em/ — an agent daemon runs on each target
host (agent/agent.go, operator.proto): the operator pushes build/config
files to it, then drives Setup/Start/Stop/Teardown of the service process
and watches agent heartbeats; node/cluster layers (m3em/node, m3em/cluster)
orchestrate placements of such nodes for destructive tests (dtest).

Here the agent is an HTTP service managing child processes under a working
directory; the operator is its client. Orchestration lives in
testing/dtest.py. Process targets are command argv lists — for this
framework that's ``python -m m3_tpu.services.dbnode ...``.
"""

from __future__ import annotations

import base64
import json
import os
import shutil
import signal
import subprocess
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class AgentServer:
    """One host's agent: setup files + manage one process per target id."""

    def __init__(self, base_dir: str, host: str = "127.0.0.1", port: int = 0) -> None:
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)
        self._procs: dict[str, subprocess.Popen] = {}
        self._argv: dict[str, list[str]] = {}
        self._lock = threading.Lock()
        # monotonic: uptime is a duration, and NTP steps must not warp it
        self.started_at = time.monotonic()
        # panicmon (x/panicmon + agent/heartbeater.go): watch spawned
        # processes for SILENT death — an exit not requested through
        # op_stop/op_teardown is recorded and surfaces in /heartbeat
        self._expected_exit: set[str] = set()
        self._exit_events: list[dict] = []
        self._reported_exit: set[str] = set()
        self._watch_stop = threading.Event()
        threading.Thread(
            target=self._watch_loop, daemon=True, name="m3em-panicmon"
        ).start()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code, obj):
                payload = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                if self.path == "/heartbeat":
                    with outer._lock:
                        procs = {
                            tid: {
                                "pid": p.pid,
                                "running": p.poll() is None,
                                "returncode": p.returncode,
                            }
                            for tid, p in outer._procs.items()
                        }
                    with outer._lock:
                        exits = list(outer._exit_events)
                    self._reply(200, {"ok": True, "uptime": time.monotonic() - outer.started_at,
                                      "processes": procs, "exits": exits})
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    op = self.path.strip("/")
                    fn = getattr(outer, f"op_{op}", None)
                    if fn is None:
                        self._reply(404, {"error": f"unknown op {op}"})
                        return
                    self._reply(200, fn(body))
                except Exception as exc:
                    self._reply(400, {"error": str(exc)})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True).start()

    # --- operator ops (operator.proto Setup/Start/Stop/Teardown) ---

    def _dir(self, target: str) -> str:
        safe = "".join(c for c in target if c.isalnum() or c in "-_")
        if not safe:
            raise ValueError(f"bad target id {target!r}")
        return os.path.join(self.base_dir, safe)

    def op_setup(self, body: dict) -> dict:
        """Create the target's working dir and place transferred files."""
        target = body["target"]
        d = self._dir(target)
        os.makedirs(d, exist_ok=True)
        for rel, b64 in (body.get("files") or {}).items():
            if os.path.isabs(rel) or ".." in rel.split("/"):
                raise ValueError(f"bad file path {rel!r}")
            path = os.path.join(d, rel)
            os.makedirs(os.path.dirname(path) or d, exist_ok=True)
            with open(path, "wb") as f:
                f.write(base64.b64decode(b64))
        with self._lock:
            self._argv[target] = list(body["argv"])
        return {"dir": d}

    def op_start(self, body: dict) -> dict:
        target = body["target"]
        with self._lock:
            self._expected_exit.discard(target)
            self._reported_exit.discard(target)
            argv = self._argv.get(target)
            if argv is None:
                raise ValueError(f"target {target} not set up")
            cur = self._procs.get(target)
            if cur is not None and cur.poll() is None:
                return {"pid": cur.pid, "alreadyRunning": True}
            proc = subprocess.Popen(
                argv,
                cwd=self._dir(target),
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                env={**os.environ, **(body.get("env") or {})},
            )
            self._procs[target] = proc
        return {"pid": proc.pid}

    def _watch_loop(self) -> None:
        while not self._watch_stop.wait(0.2):
            with self._lock:
                for tid, p in self._procs.items():
                    if (
                        p.poll() is not None
                        and tid not in self._expected_exit
                        and tid not in self._reported_exit
                    ):
                        self._reported_exit.add(tid)
                        self._exit_events.append(
                            {
                                "target": tid,
                                "returncode": p.returncode,
                                "pid": p.pid,
                                "at": time.time(),
                            }
                        )

    def op_stop(self, body: dict) -> dict:
        target = body["target"]
        sig = int(body.get("signal", signal.SIGTERM))
        with self._lock:
            self._expected_exit.add(target)
            proc = self._procs.get(target)
        if proc is None or proc.poll() is not None:
            return {"stopped": False}
        proc.send_signal(sig)
        try:
            proc.wait(timeout=float(body.get("timeout", 10)))
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5)
        return {"stopped": True, "returncode": proc.returncode}

    def op_teardown(self, body: dict) -> dict:
        target = body["target"]
        self.op_stop({"target": target, "signal": signal.SIGKILL, "timeout": 2})
        with self._lock:
            self._procs.pop(target, None)
            self._argv.pop(target, None)
        if body.get("removeData", True):
            shutil.rmtree(self._dir(target), ignore_errors=True)
        return {"torn": True}

    def close(self) -> None:
        self._watch_stop.set()
        with self._lock:
            targets = list(self._procs)
        for t in targets:
            self.op_stop({"target": t, "signal": signal.SIGKILL, "timeout": 2})
        self._server.shutdown()


class AgentClient:
    """Operator-side client of one agent (m3em operator role)."""

    def __init__(self, host: str, port: int) -> None:
        self.base = f"http://{host}:{port}"

    def _post(self, op: str, **body):
        req = urllib.request.Request(
            f"{self.base}/{op}", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        return out

    def heartbeat(self) -> dict:
        with urllib.request.urlopen(f"{self.base}/heartbeat", timeout=5) as r:
            return json.loads(r.read())

    def setup(self, target: str, argv: list[str], files: dict[str, bytes] | None = None):
        return self._post(
            "setup",
            target=target,
            argv=argv,
            files={
                k: base64.b64encode(v).decode() for k, v in (files or {}).items()
            },
        )

    def start(self, target: str, env: dict | None = None):
        return self._post("start", target=target, env=env or {})

    def stop(self, target: str, sig: int = signal.SIGTERM, timeout: float = 10):
        return self._post("stop", target=target, signal=int(sig), timeout=timeout)

    def teardown(self, target: str, remove_data: bool = True):
        return self._post("teardown", target=target, removeData=remove_data)
