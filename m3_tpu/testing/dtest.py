"""dtest-role destructive test harness over m3em agents.

Reference: /root/reference/src/cmd/tools/dtest/ — scripted destructive
scenarios (seeded bootstrap, node stop/start, add/replace) driven through
m3em-managed real processes, asserting the cluster converges. Here the
harness provisions REAL dbnode processes through testing/m3em.py agents,
seeds data over the socket client, and exposes the destructive primitives
scenarios compose.
"""

from __future__ import annotations

import sys
import time

from ..client.session import Session
from ..cluster.placement import build_initial_placement
from ..cluster.topology import ConsistencyLevel, TopologyMap
from ..net.client import RemoteNode
from ..testing.m3em import AgentClient, AgentServer
from ..utils.xtime import Unit

NANOS = 1_000_000_000


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class DTestHarness:
    """Provision + destroy dbnode processes through agents.

    ``agents`` maps node id -> AgentClient; one local AgentServer per node
    is created when none are given (the single-host dtest docker mode)."""

    def __init__(
        self,
        node_ids: list[str],
        base_dir: str,
        num_shards: int = 4,
        replica_factor: int = 2,
        agents: dict[str, AgentClient] | None = None,
    ) -> None:
        self.node_ids = list(node_ids)
        self.base_dir = base_dir
        self.num_shards = num_shards
        self._own_agents: list[AgentServer] = []
        if agents is None:
            agents = {}
            for nid in node_ids:
                srv = AgentServer(f"{base_dir}/agent-{nid}")
                self._own_agents.append(srv)
                agents[nid] = AgentClient("127.0.0.1", srv.port)
        self.agents = agents
        self.ports = {nid: _free_port() for nid in node_ids}
        self.placement = build_initial_placement(
            self.node_ids, num_shards, replica_factor
        )
        self.nodes: dict[str, RemoteNode] = {}

    def node_argv(self, nid: str) -> list[str]:
        shards = ",".join(
            str(s) for s in sorted(self.placement.instances[nid].shards)
        )
        return [
            sys.executable,
            "-m",
            "m3_tpu.services.dbnode",
            "--base-dir",
            "data",  # relative to the agent target dir
            "--port",
            str(self.ports[nid]),
            "--node-id",
            nid,
            "--num-shards",
            str(self.num_shards),
            "--shards",
            shards,
        ]

    # --- lifecycle primitives (dtest harness verbs) ---

    def setup_all(self) -> None:
        for nid in self.node_ids:
            self.agents[nid].setup(nid, self.node_argv(nid))

    def start(self, nid: str) -> None:
        self.agents[nid].start(nid, env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": self._pythonpath()})
        self.nodes[nid] = RemoteNode("127.0.0.1", self.ports[nid], node_id=nid)
        self._await_health(nid)

    @staticmethod
    def _pythonpath() -> str:
        import m3_tpu

        import os

        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(m3_tpu.__file__)))
        existing = os.environ.get("PYTHONPATH", "")
        return f"{pkg_root}:{existing}" if existing else pkg_root

    def _await_health(self, nid: str, timeout: float = 30) -> None:
        deadline = time.monotonic() + timeout
        node = self.nodes[nid]
        while time.monotonic() < deadline:
            try:
                if node.health().get("bootstrapped"):
                    return
            except Exception:
                # m3lint: disable=M3L007 -- poll loop probing a node that is still booting; the timeout below reports failure
                pass
            time.sleep(0.2)
        raise TimeoutError(f"{nid} did not become healthy")

    def start_all(self) -> None:
        for nid in self.node_ids:
            self.start(nid)

    def kill(self, nid: str) -> None:
        import signal

        self.agents[nid].stop(nid, sig=signal.SIGKILL, timeout=5)

    def restart(self, nid: str) -> None:
        self.start(nid)

    def session(self, read_cl=ConsistencyLevel.MAJORITY,
                write_cl=ConsistencyLevel.MAJORITY) -> Session:
        return Session(
            topology=TopologyMap(self.placement),
            nodes=self.nodes,
            read_consistency=read_cl,
            write_consistency=write_cl,
        )

    def seed(self, n_series: int = 4, n_points: int = 10,
             t0: int = 1000 * NANOS) -> dict[bytes, list[float]]:
        """Seeded write load (dtest seeded-bootstrap input)."""
        session = self.session()
        written: dict[bytes, list[float]] = {}
        for i in range(n_series):
            sid = b"dtest-series-%d" % i
            vals = []
            for j in range(n_points):
                v = float(i * 100 + j)
                session.write(sid, t0 + j * 10 * NANOS, v, Unit.SECOND)
                vals.append(v)
            written[sid] = vals
        return written

    def close(self) -> None:
        for nid in self.node_ids:
            try:
                self.agents[nid].teardown(nid)
            except Exception:
                # m3lint: disable=M3L007 -- best-effort teardown of a possibly already-dead test process
                pass
        for srv in self._own_agents:
            srv.close()
