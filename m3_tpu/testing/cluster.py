"""In-process multi-node cluster fixture with a fake control plane.

Reference: /root/reference/src/dbnode/integration/ — testSetup boots real
m3dbnode instances in-process (setup.go:96) against fake in-memory cluster
services (integration/fake/cluster_services.go); quorum, peers-bootstrap,
node-add and repair tests all run on this fixture. Same pattern here: real
storage.Database per node, shared KVStore control plane, fault injection by
toggling node.is_up.
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass, field

from ..cluster.kv import KVStore
from ..cluster.placement import (
    Placement,
    PlacementService,
    ShardState,
    add_instance,
    build_initial_placement,
)
from ..cluster.topology import ConsistencyLevel, DynamicTopology, TopologyMap
from ..client.session import Session
from ..storage.database import Database, NamespaceOptions
from ..utils.hash import shard_for
from ..utils.xtime import Unit


class Node:
    """One in-process storage node (the role of a full m3dbnode)."""

    def __init__(self, node_id: str, base_dir: str, num_shards: int, ns_opts: NamespaceOptions) -> None:
        self.id = node_id
        self.num_shards = num_shards
        self.db = Database(os.path.join(base_dir, node_id), num_shards=num_shards)
        self.db.create_namespace("default", ns_opts)
        self.is_up = True
        self.assigned_shards: set[int] = set()

    # --- node RPC surface (tchannelthrift node service equivalent) ---

    def write(self, ns, sid, t, v, unit=Unit.SECOND):
        if not self.is_up:
            raise ConnectionError(f"{self.id} down")
        self.db.write(ns, sid, t, v, unit)

    def write_tagged(self, ns, tags, t, v, unit=Unit.SECOND):
        if not self.is_up:
            raise ConnectionError(f"{self.id} down")
        return self.db.write_tagged(ns, tags, t, v, unit)

    def write_tagged_batch(self, ns, entries):
        if not self.is_up:
            raise ConnectionError(f"{self.id} down")
        return self.db.write_tagged_batch(ns, entries)

    def fetch_tagged(self, ns, query, start, end, limit=None):
        if not self.is_up:
            raise ConnectionError(f"{self.id} down")
        return self.db.fetch_tagged(ns, query, start, end, limit=limit)

    def read(self, ns, sid, start, end):
        if not self.is_up:
            raise ConnectionError(f"{self.id} down")
        return self.db.read(ns, sid, start, end)

    def fetch_blocks(self, ns, sid, start, end):
        if not self.is_up:
            raise ConnectionError(f"{self.id} down")
        return self.db.fetch_blocks(ns, sid, start, end)

    def owned_shards(self) -> set[int]:
        return self.assigned_shards

    def query_ids(self, ns, query, start, end, limit=None):
        if not self.is_up:
            raise ConnectionError(f"{self.id} down")
        result = self.db.query_ids(ns, query, start, end, limit=limit)
        return {
            "docs": [[d.id, list(d.fields)] for d in result.docs],
            "exhaustive": result.exhaustive,
        }

    def aggregate_query(self, ns, query, start, end, field_filter=None):
        if not self.is_up:
            raise ConnectionError(f"{self.id} down")
        return self.db.aggregate_query(ns, query, start, end, field_filter=field_filter)

    def stream_shard(self, ns, shard):
        """Peer streaming: all (sid, tags, datapoints) owned by one shard."""
        if not self.is_up:
            raise ConnectionError(f"{self.id} down")
        return self.db.stream_shard(ns, shard)

    def block_metadata(self, ns, shard):
        if not self.is_up:
            raise ConnectionError(f"{self.id} down")
        from ..storage.repair import block_metadata

        return block_metadata(self.db, ns, shard)

    def stream_series_blocks(self, ns, shard, items):
        if not self.is_up:
            raise ConnectionError(f"{self.id} down")
        from ..storage.repair import stream_series_blocks

        return stream_series_blocks(self.db, ns, items)


@dataclass
class LocalCluster:
    """testSetup: N nodes + fake control plane + cluster session."""

    num_nodes: int = 3
    num_shards: int = 8
    replica_factor: int = 3
    ns_opts: NamespaceOptions = field(
        default_factory=lambda: NamespaceOptions(block_size_nanos=2 * 3600 * 10**9)
    )
    base_dir: str | None = None

    def __post_init__(self) -> None:
        self.base_dir = self.base_dir or tempfile.mkdtemp(prefix="m3tpu-cluster-")
        self.kv = KVStore()
        self.placement_svc = PlacementService(self.kv)
        ids = [f"node{i}" for i in range(self.num_nodes)]
        self.nodes = {
            nid: Node(nid, self.base_dir, self.num_shards, self.ns_opts) for nid in ids
        }
        placement = build_initial_placement(ids, self.num_shards, self.replica_factor)
        self._apply_assignments(placement)
        self.placement_svc.set(placement)
        self.topology = DynamicTopology(self.placement_svc)
        self.topology.listen(lambda m: self._apply_assignments(m.placement))

    def _apply_assignments(self, placement: Placement) -> None:
        for nid, node in self.nodes.items():
            inst = placement.instances.get(nid)
            node.assigned_shards = set(inst.shards) if inst else set()

    def session(
        self,
        write_cl: ConsistencyLevel = ConsistencyLevel.MAJORITY,
        read_cl: ConsistencyLevel = ConsistencyLevel.MAJORITY,
    ) -> Session:
        return Session(
            topology=self.topology.map,
            nodes=self.nodes,
            write_consistency=write_cl,
            read_consistency=read_cl,
        )

    # --- elastic topology (cluster_add_one_node_test.go pattern) ---

    def add_node(self, node_id: str) -> Node:
        node = Node(node_id, self.base_dir, self.num_shards, self.ns_opts)
        self.nodes[node_id] = node
        placement = self.placement_svc.get()
        placement = add_instance(placement, node_id)
        self.placement_svc.set(placement)
        # peers bootstrap: stream INITIALIZING shards from their source
        session = self.session()
        inst = placement.instances[node_id]
        for shard_id, a in inst.shards.items():
            if a.state != ShardState.INITIALIZING or not a.source_instance:
                continue
            for sid, tags, dps in session.stream_shard_from_peer(a.source_instance, shard_id):
                for dp in dps:
                    if tags:
                        node.write_tagged("default", tags, dp.timestamp, dp.value, dp.unit)
                    else:
                        node.write("default", sid, dp.timestamp, dp.value, dp.unit)
            a.state = ShardState.AVAILABLE
        self.placement_svc.set(placement)
        return node

    # --- repair (storage/repair.py: checksum-diff replicas, stream diffs) ---

    def repair(self, ns: str = "default") -> int:
        """Active anti-entropy over all live replicas: each node repairs its
        owned shards against its peers via the storage-layer checksum diff
        (storage/repair.go semantics). Returns points merged."""
        from ..storage.repair import repair_database

        merged = 0
        placement = self.placement_svc.get()
        for nid, node in self.nodes.items():
            if not node.is_up:
                continue
            inst = placement.instances.get(nid)
            if inst is None:
                continue
            for shard_id in sorted(inst.shards):
                peers = [
                    self.nodes[i.id]
                    for i in placement.instances_for_shard(shard_id)
                    if i.id != nid and self.nodes[i.id].is_up
                ]
                if not peers:
                    continue
                r = repair_database(
                    node.db, ns, peers, shard_ids=[shard_id]
                )
                if r.peer_errors:
                    # a failed repair must not read as "converged"
                    raise RuntimeError(
                        f"repair errors on {nid} shard {shard_id}: {r.peer_errors}"
                    )
                merged += r.points_merged
        return merged
