"""Test-facing fault injection: seeded chaos plans for clusters.

The FaultPlan core lives in :mod:`m3_tpu.net.faults` (stdlib + instrument
only) because the server seam must consult a plan without importing this
package — ``m3_tpu.testing.__init__`` forces a virtual CPU mesh into the
process. This module re-exports the core and adds what only tests need:

- :class:`FaultyNode`: wrap any in-process node (testing/cluster.Node or a
  RemoteNode) so every node-method call first consults the plan — the
  in-process equivalent of a lossy/partitioned network path to that peer;
- :func:`wrap_nodes`: wrap a whole Session ``nodes`` dict at once;
- :func:`env_with_plan`: an environ dict that installs the plan in spawned
  servers (testing/proc_cluster ``node_env`` seam) via M3_TPU_FAULT_PLAN.

Example chaos setup (20% request drops everywhere + node2 partitioned)::

    plan = FaultPlan([FaultRule(drop=0.2)], seed=7)
    cut = FaultPlan([FaultRule(peer="node2", partition=True)], seed=7)
    session.nodes = wrap_nodes(session.nodes, plan)       # in-process
    ProcCluster(node_env={"node2": env_with_plan(cut)})   # real processes
"""

from __future__ import annotations

import os

from ..net.faults import (  # noqa: F401  (re-exported surface)
    FAULT_PLAN_ENV,
    FaultInjectedError,
    FaultPlan,
    FaultRule,
    plan_from_env,
)
from ..storage.faults import (  # noqa: F401  (re-exported surface)
    CRASH_POINT_ENV,
    CRASH_POINTS,
    DISK_FAULT_PLAN_ENV,
    DiskFaultError,
    DiskFaultPlan,
    DiskFaultRule,
    DiskFullError,
)
from ..storage.faults import (
    plan_from_env as disk_plan_from_env,  # noqa: F401
)

__all__ = [
    "FAULT_PLAN_ENV",
    "FaultInjectedError",
    "FaultPlan",
    "FaultRule",
    "FaultyNode",
    "env_with_plan",
    "plan_from_env",
    "wrap_nodes",
    # disk fault-injection surface (storage/faults.py)
    "CRASH_POINT_ENV",
    "CRASH_POINTS",
    "DISK_FAULT_PLAN_ENV",
    "DiskFaultError",
    "DiskFaultPlan",
    "DiskFaultRule",
    "DiskFullError",
    "disk_plan_from_env",
    "env_with_crash_point",
    "env_with_disk_plan",
]


class FaultyNode:
    """Transparent proxy over a node object applying a FaultPlan to every
    method call (peer = the node's id): injected drops surface as
    ConnectionError, injected errors as the typed retryable RemoteError —
    exactly what the session sees from a real faulty transport."""

    def __init__(self, node, plan: FaultPlan, peer: str | None = None) -> None:
        self._node = node
        self._plan = plan
        self.peer = peer or getattr(node, "id", "?")

    @property
    def id(self):
        return self._node.id

    @property
    def is_up(self):
        return self._node.is_up

    def __getattr__(self, name: str):
        attr = getattr(self._node, name)
        if not callable(attr):
            return attr
        plan, peer = self._plan, self.peer

        def faulted(*args, **kwargs):
            plan.apply_client(name, peer)
            return attr(*args, **kwargs)

        return faulted


def wrap_nodes(nodes: dict, plan: FaultPlan) -> dict:
    """A copy of a Session ``nodes`` dict with every node behind the plan."""
    return {host: FaultyNode(node, plan) for host, node in nodes.items()}


def env_with_plan(plan: FaultPlan, base: dict | None = None) -> dict:
    """Env-var overlay installing ``plan`` in a spawned server process."""
    env = dict(base or {})
    env[FAULT_PLAN_ENV] = plan.to_json()
    return env


def full_env_with_plan(plan: FaultPlan) -> dict:
    """A COMPLETE environ (os.environ + the plan) for subprocess spawns
    that replace the environment rather than overlaying it."""
    return env_with_plan(plan, base=dict(os.environ))


def env_with_disk_plan(plan: DiskFaultPlan, base: dict | None = None) -> dict:
    """Env-var overlay installing a DISK fault plan (storage/faults.py) in
    a spawned server process (proc_cluster ``node_env`` seam)."""
    env = dict(base or {})
    env[DISK_FAULT_PLAN_ENV] = plan.to_json()
    return env


def env_with_crash_point(*sites: str, base: dict | None = None) -> dict:
    """Env-var overlay arming deterministic crash points (the process
    hard-exits with CRASH_EXIT_CODE the first time it passes any of the
    named sites — see storage.faults.CRASH_POINTS)."""
    for site in sites:
        if site not in CRASH_POINTS:
            raise ValueError(
                f"unknown crash point {site!r}; known: {CRASH_POINTS}"
            )
    env = dict(base or {})
    env[CRASH_POINT_ENV] = ",".join(sites)
    return env
