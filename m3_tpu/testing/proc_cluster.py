"""Multi-process cluster fixture: real node processes on localhost sockets.

Reference: /root/reference/src/dbnode/integration + dtest — the reference's
integration tier runs real node binaries against each other. Here each node
is a `python -m m3_tpu.services.dbnode` subprocess serving the net RPC
protocol; the Session speaks sockets via net.client.RemoteNode, so quorum /
node-down behavior crosses real serialization + process boundaries.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field

from ..client.session import Session
from ..cluster.placement import build_initial_placement
from ..cluster.topology import ConsistencyLevel, TopologyMap
from ..net.client import RemoteNode


@dataclass
class ProcNode:
    node_id: str
    proc: subprocess.Popen
    client: RemoteNode

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        if self.alive:
            self.proc.kill()
            self.proc.wait(timeout=10)
        self.client.close()

    def terminate(self) -> None:
        if self.alive:
            self.proc.send_signal(signal.SIGTERM)
            self.proc.wait(timeout=10)
        self.client.close()


@dataclass
class ProcCluster:
    num_nodes: int = 3
    num_shards: int = 8
    replica_factor: int = 3
    block_size_secs: int = 2 * 3600
    base_dir: str | None = None
    extra_args: list = field(default_factory=list)
    nodes: dict = field(default_factory=dict)

    def __post_init__(self) -> None:
        self.base_dir = self.base_dir or tempfile.mkdtemp(prefix="m3tpu-proc-")
        ids = [f"node{i}" for i in range(self.num_nodes)]
        self.placement = build_initial_placement(
            ids, self.num_shards, self.replica_factor
        )
        for nid in ids:
            self.nodes[nid] = self._spawn(nid)
        for nid, pn in self.nodes.items():
            inst = self.placement.instances[nid]
            pn.client.assign_shards(set(inst.shards))

    def _spawn(self, node_id: str, port: int = 0) -> ProcNode:
        cmd = [
            sys.executable,
            "-m",
            "m3_tpu.services.dbnode",
            "--base-dir",
            os.path.join(self.base_dir, node_id),
            "--port",
            str(port),
            "--node-id",
            node_id,
            "--num-shards",
            str(self.num_shards),
            "--block-size-secs",
            str(self.block_size_secs),
            "--no-mediator",
            *self.extra_args,
        ]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
        )
        # a reader thread owns the (buffered) pipe; the main thread waits on
        # a queue with a deadline, so a child hanging before LISTENING (or a
        # line already sitting in the TextIOWrapper buffer, which select(2)
        # on the raw fd cannot see) can neither block nor be missed
        import queue as _queue
        import threading

        lines: _queue.Queue = _queue.Queue()

        def _pump():
            for ln in proc.stdout:
                lines.put(ln)
            lines.put(None)

        threading.Thread(target=_pump, daemon=True).start()
        deadline = time.time() + 60
        line = ""
        while True:
            remaining = deadline - time.time()
            if remaining <= 0:
                proc.kill()
                raise TimeoutError(f"{node_id} did not start: {line!r}")
            try:
                item = lines.get(timeout=min(remaining, 1.0))
            except _queue.Empty:
                if proc.poll() is not None:
                    raise RuntimeError(f"{node_id} died at startup")
                continue
            if item is None:
                raise RuntimeError(f"{node_id} died at startup")
            line = item
            if line.startswith("LISTENING"):
                break
        _, host, port_s = line.split()
        client = RemoteNode(host, int(port_s), node_id=node_id)
        return ProcNode(node_id, proc, client)

    def restart(self, node_id: str) -> None:
        """Kill + respawn a node on a fresh port (data dir persists, so the
        node bootstraps from its WAL/filesets)."""
        self.nodes[node_id].kill()
        self.nodes[node_id] = self._spawn(node_id)
        inst = self.placement.instances[node_id]
        self.nodes[node_id].client.assign_shards(set(inst.shards))

    def session(
        self,
        write_cl: ConsistencyLevel = ConsistencyLevel.MAJORITY,
        read_cl: ConsistencyLevel = ConsistencyLevel.MAJORITY,
    ) -> Session:
        return Session(
            topology=TopologyMap(self.placement),
            nodes={nid: pn.client for nid, pn in self.nodes.items()},
            write_consistency=write_cl,
            read_consistency=read_cl,
        )

    def close(self) -> None:
        for pn in self.nodes.values():
            pn.kill()
