"""Multi-process cluster fixture: real node processes on localhost sockets,
coordinated through a real networked control plane.

Reference: /root/reference/src/dbnode/integration + dtest — the reference's
integration tier runs real node binaries against each other with etcd (or a
fake) as the control plane. Here:

- one `python -m m3_tpu.services.kvnode` subprocess is the control plane
  (etcd's role);
- each node is a `python -m m3_tpu.services.dbnode --kv-endpoint ...`
  subprocess that advertises itself, heartbeats, watches the placement and
  peers-bootstraps gained shards — the fixture never pushes shard
  assignments; it only writes the placement into the KV, exactly like an
  operator using the placement API.
"""

from __future__ import annotations

import os
import queue as _queue
import signal
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field

from ..client.session import Session
from ..cluster.kv_service import RemoteKVStore
from ..cluster.placement import PlacementService, build_initial_placement
from ..cluster.topology import ConsistencyLevel, TopologyMap
from ..net.client import RemoteNode

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))


def _spawn_listening(cmd: list[str], what: str, timeout: float = 60.0,
                     collect: dict | None = None,
                     expect_markers: set[str] | None = None,
                     env_extra: dict | None = None):
    """Start a subprocess that prints LISTENING <host> <port>; returns
    (proc, host, port). Named marker lines (``expect_markers``, e.g.
    {"MSG_LISTENING"}) printed before/after it are collected into
    ``collect`` as (host, port), read from the same pump (reading
    proc.stdout directly would race the pump thread that owns the pipe)."""
    expect_markers = expect_markers or set()

    def _maybe_collect(parts) -> None:
        if (
            collect is not None
            and len(parts) == 3
            and parts[0] in expect_markers
            and parts[2].isdigit()
        ):
            collect[parts[0]] = (parts[1], int(parts[2]))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if env_extra:
        env.update(env_extra)
    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        env=env,
        cwd=_REPO_ROOT,
    )
    # a reader thread owns the (buffered) pipe; the main thread waits on a
    # queue with a deadline, so a child hanging before LISTENING (or a line
    # already sitting in the TextIOWrapper buffer, which select(2) on the
    # raw fd cannot see) can neither block nor be missed
    lines: _queue.Queue = _queue.Queue()

    def _pump():
        for ln in proc.stdout:
            lines.put(ln)
        lines.put(None)

    threading.Thread(target=_pump, daemon=True).start()
    deadline = time.monotonic() + timeout
    line = ""
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            proc.kill()
            raise TimeoutError(f"{what} did not start: {line!r}")
        try:
            item = lines.get(timeout=min(remaining, 1.0))
        except _queue.Empty:
            if proc.poll() is not None:
                raise RuntimeError(f"{what} died at startup")
            continue
        if item is None:
            raise RuntimeError(f"{what} died at startup")
        line = item
        _maybe_collect(line.split())
        if line.startswith("LISTENING"):
            break
    _, host, port_s = line.split()
    # expected markers may follow LISTENING: wait until all are present
    if expect_markers:
        wait_until = time.monotonic() + 10
        while time.monotonic() < wait_until and not expect_markers <= set(collect or {}):
            try:
                item = lines.get(timeout=0.2)
            except _queue.Empty:
                continue
            if item is None:
                break
            _maybe_collect(item.split())
    return proc, host, int(port_s)


@dataclass
class ProcNode:
    node_id: str
    proc: subprocess.Popen
    client: RemoteNode

    @property
    def endpoint(self) -> str:
        return f"{self.client.host}:{self.client.port}"

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        if self.alive:
            self.proc.kill()
            self.proc.wait(timeout=10)
        self.client.close()

    def terminate(self) -> None:
        if self.alive:
            self.proc.send_signal(signal.SIGTERM)
            self.proc.wait(timeout=10)
        self.client.close()


def spawn_kv_quorum(n: int, base_dir: str, what: str = "kvnode"):
    """Spawn an n-replica raft kvnode quorum (etcd-cluster role). Returns
    (procs, endpoints): every replica is configured with the full member
    map over the raft_configure RPC and the call blocks until a leader is
    elected."""
    procs, endpoints = [], {}
    for i in range(n):
        nid = f"kv{i}"
        proc, host, port = _spawn_listening(
            [
                sys.executable, "-m", "m3_tpu.services.kvnode",
                "--port", "0", "--raft", "--node-id", nid,
                "--data-dir", os.path.join(base_dir, nid),
            ],
            f"{what}-{nid}",
        )
        procs.append(proc)
        endpoints[nid] = f"{host}:{port}"
    from ..net.client import RpcClient

    clients = []
    try:
        for nid, ep in endpoints.items():
            c = RpcClient.connect(ep)
            clients.append(c)
            c._call("raft_configure", members=endpoints)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            leaders = set()
            for c in clients:
                try:
                    st = c._call("raft_status")
                except Exception:
                    continue
                if st["role"] == "leader":
                    leaders.add(st["id"])
            if len(leaders) == 1:
                return procs, list(endpoints.values())
            time.sleep(0.05)
        raise TimeoutError("kv quorum did not elect a leader")
    except BaseException:
        for p in procs:
            p.kill()
        raise
    finally:
        for c in clients:
            c.close()


@dataclass
class ProcCluster:
    num_nodes: int = 3
    num_shards: int = 8
    replica_factor: int = 3
    block_size_secs: int = 2 * 3600
    heartbeat_timeout: float = 2.0
    base_dir: str | None = None
    extra_args: list = field(default_factory=list)
    # env-var overlays for spawned dbnode processes: extra_env applies to
    # every node, node_env[node_id] to one — the seam chaos runs use to
    # install per-node fault plans (testing/faults.env_with_plan)
    extra_env: dict = field(default_factory=dict)
    node_env: dict = field(default_factory=dict)
    nodes: dict = field(default_factory=dict)
    kv_replicas: int = 1  # >1: raft quorum of standalone kvnodes
    # embedded seeds: every dbnode ALSO runs a raft KV replica in-process
    # (server.go:266-324 embedded etcd) — no standalone kvnode at all
    embedded_kv: bool = False

    def __post_init__(self) -> None:
        self.base_dir = self.base_dir or tempfile.mkdtemp(prefix="m3tpu-proc-")
        if self.embedded_kv:
            self._start_embedded()
            return
        if self.kv_replicas > 1:
            self.kv_procs, kv_eps = spawn_kv_quorum(
                self.kv_replicas, os.path.join(self.base_dir, "kv")
            )
            self.kv_endpoint = ",".join(kv_eps)
        else:
            kv_proc, kv_host, kv_port = _spawn_listening(
                [sys.executable, "-m", "m3_tpu.services.kvnode", "--port", "0"],
                "kvnode",
            )
            self.kv_procs = [kv_proc]
            self.kv_endpoint = f"{kv_host}:{kv_port}"
        try:
            self.kv = RemoteKVStore.connect(self.kv_endpoint)
            self.placement_svc = PlacementService(self.kv)

            ids = [f"node{i}" for i in range(self.num_nodes)]
            for nid in ids:
                self.nodes[nid] = self._spawn(nid)
            placement = build_initial_placement(
                ids, self.num_shards, self.replica_factor
            )
            for nid in ids:
                placement.instances[nid].endpoint = self.nodes[nid].endpoint
            self.placement_svc.set(placement)
            self.wait_for_shards()
        except BaseException:
            # a half-started cluster must not orphan its processes — the
            # fixture object never reaches the caller, so close() would
            # never run
            self.close()
            raise

    def _start_embedded(self) -> None:
        """Seed-node deployment: each dbnode carries an embedded raft KV
        replica; the fixture collects every seed's KV endpoint, configures
        the quorum, then writes the placement like an operator."""
        from ..net.client import RpcClient

        self.kv_procs = []
        ids = [f"node{i}" for i in range(self.num_nodes)]
        kv_members: dict[str, str] = {}
        try:
            for nid in ids:
                collect: dict = {}
                cmd = [
                    sys.executable, "-m", "m3_tpu.services.dbnode",
                    "--base-dir", os.path.join(self.base_dir, nid),
                    "--port", "0", "--node-id", nid,
                    "--num-shards", str(self.num_shards),
                    "--block-size-secs", str(self.block_size_secs),
                    "--heartbeat-timeout", str(self.heartbeat_timeout),
                    "--no-mediator", "--embed-kv",
                    *self.extra_args,
                ]
                proc, host, port = _spawn_listening(
                    cmd, nid, collect=collect, expect_markers={"KV_LISTENING"},
                    env_extra={**self.extra_env, **self.node_env.get(nid, {})},
                )
                kh, kp = collect["KV_LISTENING"]
                kv_members[f"kv-{nid}"] = f"{kh}:{kp}"
                self.nodes[nid] = ProcNode(nid, proc, RemoteNode(host, port, node_id=nid))
            for ep in kv_members.values():
                c = RpcClient.connect(ep)
                c._call("raft_configure", members=kv_members)
                c.close()
            # wait for a single leader across the embedded quorum
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                leaders = set()
                for ep in kv_members.values():
                    c = RpcClient.connect(ep)
                    try:
                        st = c._call("raft_status")
                        if st["role"] == "leader":
                            leaders.add(st["id"])
                    except Exception:
                        # m3lint: disable=M3L007 -- raft_status probe of a seed that may not be up yet; the wait loop retries
                        pass
                    finally:
                        c.close()
                if len(leaders) == 1:
                    break
                time.sleep(0.05)
            else:
                raise TimeoutError("embedded KV quorum did not elect")
            self.kv_endpoint = ",".join(kv_members.values())
            self.kv = RemoteKVStore.connect(self.kv_endpoint)
            self.placement_svc = PlacementService(self.kv)
            placement = build_initial_placement(
                ids, self.num_shards, self.replica_factor
            )
            for nid in ids:
                placement.instances[nid].endpoint = self.nodes[nid].endpoint
            self.placement_svc.set(placement)
            self.wait_for_shards()
        except BaseException:
            self.close()
            raise

    @property
    def placement(self):
        return self.placement_svc.get()

    def _spawn(self, node_id: str, port: int = 0) -> ProcNode:
        cmd = [
            sys.executable,
            "-m",
            "m3_tpu.services.dbnode",
            "--base-dir",
            os.path.join(self.base_dir, node_id),
            "--port",
            str(port),
            "--node-id",
            node_id,
            "--num-shards",
            str(self.num_shards),
            "--block-size-secs",
            str(self.block_size_secs),
            "--kv-endpoint",
            self.kv_endpoint,
            "--heartbeat-timeout",
            str(self.heartbeat_timeout),
            "--no-mediator",
            *self.extra_args,
        ]
        proc, host, port_n = _spawn_listening(
            cmd, node_id,
            env_extra={**self.extra_env, **self.node_env.get(node_id, {})},
        )
        client = RemoteNode(host, port_n, node_id=node_id)
        return ProcNode(node_id, proc, client)

    def spawn_spare(self, node_id: str) -> ProcNode:
        """A node process that advertises + heartbeats but owns no shards
        until the placement says so (the replacement pool)."""
        pn = self._spawn(node_id)
        self.nodes[node_id] = pn
        return pn

    def wait_for_shards(self, timeout: float = 30.0) -> None:
        """Block until every placed, live node's served shard set matches
        the placement (watch propagation is asynchronous)."""
        deadline = time.monotonic() + timeout
        while True:
            p = self.placement_svc.get()
            pending = []
            for nid, inst in (p.instances if p else {}).items():
                pn = self.nodes.get(nid)
                if pn is None or not pn.alive:
                    continue
                try:
                    owned = pn.client.owned_shards(cache_secs=0.0)
                except Exception:
                    pending.append((nid, "unreachable"))
                    continue
                want = set(inst.shards)
                if owned != want:
                    pending.append((nid, f"{sorted(owned)} != {sorted(want)}"))
            if not pending:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(f"shard propagation timed out: {pending}")
            time.sleep(0.05)

    def restart(self, node_id: str) -> None:
        """Kill + respawn a node on a fresh port (data dir persists, so the
        node bootstraps from its WAL/filesets); the placement's endpoint is
        updated via CAS as an operator would."""
        self.nodes[node_id].kill()
        self.nodes[node_id] = self._spawn(node_id)
        while True:
            p, version = self.placement_svc.get_versioned()
            if p is None or node_id not in p.instances:
                break
            p.instances[node_id].endpoint = self.nodes[node_id].endpoint
            try:
                self.placement_svc.check_and_set(p, version)
                break
            except ValueError:
                continue
        self.wait_for_shards()

    def session(
        self,
        write_cl: ConsistencyLevel = ConsistencyLevel.MAJORITY,
        read_cl: ConsistencyLevel = ConsistencyLevel.MAJORITY,
    ) -> Session:
        p = self.placement_svc.get()
        nodes = {}
        for nid, inst in p.instances.items():
            pn = self.nodes.get(nid)
            if pn is not None:
                nodes[nid] = pn.client
            elif inst.endpoint:
                nodes[nid] = RemoteNode.connect(inst.endpoint, node_id=nid)
        return Session(
            topology=TopologyMap(p),
            nodes=nodes,
            write_consistency=write_cl,
            read_consistency=read_cl,
        )

    def kill_kv_leader(self) -> int:
        """SIGKILL the raft leader among the KV replicas (control-plane
        fault injection); returns the index of the killed process."""
        from ..net.client import RpcClient

        for i, ep in enumerate(self.kv_endpoint.split(",")):
            c = RpcClient.connect(ep)
            try:
                st = c._call("raft_status")
            except Exception:
                continue
            finally:
                c.close()
            if st["role"] == "leader":
                self.kv_procs[i].kill()
                self.kv_procs[i].wait(timeout=10)
                return i
        raise RuntimeError("no KV leader found")

    def close(self) -> None:
        for pn in self.nodes.values():
            pn.kill()
        try:
            if getattr(self, "kv", None) is not None:
                self.kv.close()
        finally:
            for proc in getattr(self, "kv_procs", []):
                if proc.poll() is None:
                    proc.kill()
                    proc.wait(timeout=10)
