"""Runtime lock-order race harness: RacerD-shaped dynamic analysis for
tests (Blackshear et al., OOPSLA 2018 — compositional lock-order facts,
no whole-program execution needed).

What it records, per instrumented lock:

- the per-thread ACQUISITION GRAPH: an edge A→B whenever a thread
  acquires B while holding A, with the first witness stack site for each
  edge. A cycle in the merged graph is a potential deadlock even if the
  interleaving that deadlocks never ran — the classic AB/BA inversion is
  caught from two clean sequential executions.
- BLOCKING-BOUNDARY violations: a registered blocking call (e.g.
  ``jax.block_until_ready`` — PR 3's admission rule, or a socket RPC)
  executed while the thread holds any instrumented lock.

Usage (the injectable-factory seam)::

    from m3_tpu.testing.lockcheck import LockCheck

    with LockCheck.instrumented() as chk:   # patches threading.Lock/RLock
        db = Database(...)                  # locks created here are tracked
        ... run the concurrent workload ...
    chk.assert_clean()                      # raises LockOrderError on a
                                            # cycle or boundary violation

or without patching, for code that accepts a lock factory::

    chk = LockCheck()
    lock_a = chk.lock("table")
    lock_b = chk.lock("freelist")

Blocking boundaries::

    jax.block_until_ready = chk.wrap_blocking(
        jax.block_until_ready, "jax.block_until_ready")
    # or, inline at a known blocking point:
    chk.boundary("socket send")

The wrappers are full drop-in ``Lock``/``RLock`` replacements (context
manager, ``acquire(blocking, timeout)``, ``locked()``, and the
``_is_owned``/``_release_save``/``_acquire_restore`` trio so
``threading.Condition``/``Event``/``queue.Queue`` built on them keep
working). Bookkeeping never holds the checker's internal lock while
acquiring a user lock, so the harness cannot deadlock the code under
test.
"""

from __future__ import annotations

import itertools
import sys
import threading
from contextlib import contextmanager


class LockOrderError(AssertionError):
    """A lock-order cycle (potential deadlock) or a blocking-boundary
    violation witnessed by the harness."""


_INFRA_FILES = ("threading.py", "queue.py", "contextlib.py", "socketserver.py")


def _site() -> str:
    """filename:lineno of the nearest application frame (cheap frame
    walk — this runs on every instrumented acquire)."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if fn != __file__ and not fn.endswith(_INFRA_FILES):
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "?"


class LockCheck:
    """One harness instance = one merged acquisition graph."""

    def __init__(self) -> None:
        self._ids = itertools.count(1)
        self._meta: dict[int, tuple[str, str]] = {}  # id -> (name, creation site)
        # (a_id, b_id) -> (a_site, b_site): first witness of "held a,
        # acquired b" with the stack locations of the two acquires
        self._edges: dict[tuple[int, int], tuple[str, str]] = {}
        self._violations: list[str] = []
        self._tls = threading.local()
        self._mu = threading.Lock()  # guards _edges/_violations/_meta

    # -- factory seam --

    def lock(self, name: str | None = None) -> "_CheckedLock":
        return _CheckedLock(self, threading.Lock, name)

    def rlock(self, name: str | None = None) -> "_CheckedRLock":
        return _CheckedRLock(self, threading.RLock, name)

    @classmethod
    @contextmanager
    def instrumented(cls, patch_module=threading):
        """Patch ``threading.Lock``/``threading.RLock`` so every lock
        created inside the block is checked (Condition/Event/Queue pick
        the patched factories up automatically)."""
        chk = cls()
        orig_lock, orig_rlock = patch_module.Lock, patch_module.RLock
        patch_module.Lock = lambda: _CheckedLock(chk, orig_lock)
        patch_module.RLock = lambda: _CheckedRLock(chk, orig_rlock)
        try:
            yield chk
        finally:
            patch_module.Lock, patch_module.RLock = orig_lock, orig_rlock

    # -- bookkeeping (called by the wrappers) --

    def _register(self, wrapper, name: str | None) -> int:
        lock_id = next(self._ids)
        site = _site()
        with self._mu:
            self._meta[lock_id] = (name or f"lock@{site}", site)
        return lock_id

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _on_acquired(self, lock_id: int, first: bool) -> None:
        held = self._held()
        site = _site()
        if first and held:  # reentrant re-acquires add no edge
            top_id, top_site = held[-1]
            key = (top_id, lock_id)
            if key not in self._edges:  # racy pre-check; settled under _mu
                with self._mu:
                    self._edges.setdefault(key, (top_site, site))
        held.append((lock_id, site))

    def _on_released(self, lock_id: int) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == lock_id:
                del held[i]
                return

    # -- blocking boundaries --

    def boundary(self, name: str) -> None:
        """Declare 'this thread is about to block' (device sync, socket
        wait): holding any instrumented lock here is a violation."""
        held = self._held()
        if not held:
            return
        with self._mu:
            held_desc = ", ".join(
                f"{self._meta[i][0]} (acquired {site})" for i, site in held
            )
            self._violations.append(
                f"blocking boundary {name!r} reached at {_site()} while "
                f"holding: {held_desc}"
            )

    def wrap_blocking(self, fn, name: str | None = None):
        """Wrap a callable as a registered blocking boundary."""
        label = name or getattr(fn, "__name__", repr(fn))

        def wrapped(*args, **kwargs):
            self.boundary(label)
            return fn(*args, **kwargs)

        wrapped.__wrapped__ = fn
        return wrapped

    # -- verdicts --

    def cycles(self) -> list:
        """Every elementary cycle reachable in the merged acquisition
        graph, as lists of lock ids (deterministic order)."""
        with self._mu:
            edges = dict(self._edges)
        adj: dict[int, list[int]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
        for succs in adj.values():
            succs.sort()
        found: list = []
        seen_cycles: set = set()

        def dfs(start: int, node: int, path: list, on_path: set) -> None:
            for nxt in adj.get(node, ()):
                if nxt == start:
                    canon = tuple(sorted(path))
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        found.append(list(path))
                elif nxt > start and nxt not in on_path:
                    on_path.add(nxt)
                    dfs(start, nxt, path + [nxt], on_path)
                    on_path.discard(nxt)

        for start in sorted(adj):
            dfs(start, start, [start], {start})
        return found

    def _describe_cycle(self, cycle: list) -> str:
        with self._mu:
            parts = []
            ring = cycle + [cycle[0]]
            for a, b in zip(ring, ring[1:]):
                a_site, b_site = self._edges[(a, b)]
                parts.append(
                    f"  {self._meta[a][0]} (held at {a_site})\n"
                    f"    -> then acquired {self._meta[b][0]} at {b_site}"
                )
        return "\n".join(parts)

    def report(self) -> str:
        """Human-readable verdict; empty string when clean."""
        lines = []
        for cycle in self.cycles():
            names = " -> ".join(self._meta[i][0] for i in cycle + [cycle[0]])
            lines.append(
                f"lock-order cycle (potential deadlock): {names}\n"
                + self._describe_cycle(cycle)
            )
        with self._mu:
            lines.extend(self._violations)
        return "\n".join(lines)

    def assert_clean(self) -> None:
        report = self.report()
        if report:
            raise LockOrderError(report)


class _CheckedLock:
    """Drop-in non-reentrant lock recording order facts on its harness."""

    _reentrant = False

    def __init__(self, check: LockCheck, inner_factory, name: str | None = None):
        self._check = check
        self._inner = inner_factory()
        self._id = check._register(self, name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._check._on_acquired(self._id, first=self._first_acquire())
        return got

    def _first_acquire(self) -> bool:
        return True

    def release(self) -> None:
        self._inner.release()
        self._check._on_released(self._id)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _CheckedRLock(_CheckedLock):
    """Reentrant variant: re-acquiring a held lock adds no edge, and the
    Condition protocol trio keeps held-state bookkeeping truthful across
    ``Condition.wait``'s full release/reacquire."""

    _reentrant = True

    def __init__(self, check: LockCheck, inner_factory, name: str | None = None):
        super().__init__(check, inner_factory, name)
        self._depth = 0  # owner-thread recursion depth (guarded by _inner)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._depth += 1
            self._check._on_acquired(self._id, first=self._depth == 1)
        return got

    def release(self) -> None:
        self._depth -= 1
        self._inner.release()
        self._check._on_released(self._id)

    # Condition protocol (threading.Condition defers to these when the
    # underlying lock provides them)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        depth, self._depth = self._depth, 0
        for _ in range(depth):
            self._check._on_released(self._id)
        return depth, self._inner._release_save()

    def _acquire_restore(self, state):
        depth, inner_state = state
        self._inner._acquire_restore(inner_state)
        self._depth = depth
        for i in range(depth):
            self._check._on_acquired(self._id, first=i == 0)
