"""ctypes bindings for the native C++ codec (native/m3tsz.cc).

Builds lazily with g++ if the shared library is missing; every entry point
has a pure-Python fallback so the framework degrades gracefully on hosts
without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_DIR, "libm3tsz.so"))
_SRC_PATH = os.path.abspath(os.path.join(_DIR, "m3tsz.cc"))

_lib = None


class _SnapRec(ctypes.Structure):
    _pack_ = 1
    _fields_ = [
        ("off", ctypes.c_uint32),
        ("prev_time", ctypes.c_uint64),
        ("prev_delta", ctypes.c_uint64),
        ("prev_float_bits", ctypes.c_uint64),
        ("prev_xor", ctypes.c_uint64),
        ("int_val", ctypes.c_uint64),
        ("time_unit", ctypes.c_uint8),
        ("sig", ctypes.c_uint8),
        ("mult", ctypes.c_uint8),
        ("is_float", ctypes.c_uint8),
        ("flags", ctypes.c_uint8),  # bit 0: int-fast chunk; bit 1: float-fast
    ]


def _cpu_signature() -> str:
    """Identity of this host's ISA (for -march=native cache safety): a
    library built on a wider-ISA host would SIGILL here, so the cached .so
    is only trusted when the CPU flags that produced it match."""
    import hashlib
    import platform

    sig = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    sig += hashlib.sha256(line.encode()).hexdigest()[:16]
                    break
    except OSError:
        pass
    return sig


def _build() -> bool:
    if not os.path.exists(_SRC_PATH):
        return False
    try:
        subprocess.run(
            [
                "g++",
                "-O3",
                "-march=native",  # cached per-CPU-signature (see load())
                "-shared",
                "-fPIC",
                "-std=c++17",
                "-o",
                _LIB_PATH,
                _SRC_PATH,
                "-lpthread",
            ],
            check=True,
            capture_output=True,
        )
        with open(_LIB_PATH + ".buildinfo", "w") as f:
            f.write(_cpu_signature())
        return True
    except (subprocess.CalledProcessError, FileNotFoundError, OSError):
        return False


def load():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    stale = (
        os.path.exists(_LIB_PATH)
        and os.path.exists(_SRC_PATH)
        and os.path.getmtime(_SRC_PATH) > os.path.getmtime(_LIB_PATH)
    )
    if os.path.exists(_LIB_PATH) and not stale:
        # a -march=native .so copied from a wider-ISA host would SIGILL
        # (uncatchably) on first call: rebuild unless the recorded CPU
        # signature matches this host
        try:
            with open(_LIB_PATH + ".buildinfo") as f:
                stale = f.read() != _cpu_signature()
        except OSError:
            stale = True
    if (not os.path.exists(_LIB_PATH) or stale) and not _build():
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.m3tsz_encode_batch.restype = ctypes.c_int64
    lib.m3tsz_encode_series.restype = ctypes.c_int64
    lib.m3tsz_prescan.restype = ctypes.c_int32
    lib.m3tsz_prescan_batch.restype = ctypes.c_int32
    lib.m3agg_window_keys.restype = None
    lib.m3agg_count.restype = ctypes.c_int32
    lib.m3agg_pack.restype = None
    lib.m3tsz_decode_batch.restype = ctypes.c_int32
    lib.m3hash_shards.restype = None
    _lib = lib
    return lib


def available() -> bool:
    return load() is not None


def _encode_batch_native(lib, times, values, lengths, default_unit, int_optimized, n_threads, cap):
    out_buf = np.zeros(cap, np.uint8)
    offsets = np.zeros(len(lengths) + 1, np.int64)
    total = lib.m3tsz_encode_batch(
        times.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int32(len(lengths)),
        ctypes.c_int(default_unit),
        ctypes.c_int(1 if int_optimized else 0),
        out_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_int64(cap),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int32(n_threads),
    )
    return total, out_buf, offsets


def encode_batch(
    times: np.ndarray,
    values: np.ndarray,
    lengths: np.ndarray,
    default_unit: int = 1,
    int_optimized: bool = True,
    n_threads: int = 0,
) -> list[bytes]:
    """Encode N series (concatenated columns) → list of finalized streams.

    Falls back to the Python encoder when the native lib is unavailable."""
    lib = load()
    times = np.ascontiguousarray(times, np.int64)
    values = np.ascontiguousarray(values, np.float64)
    lengths = np.ascontiguousarray(lengths, np.int32)
    n = len(lengths)
    if lib is None:
        from ..codec.m3tsz import encode_series
        from ..utils.xtime import Unit

        out = []
        pos = 0
        for ln in lengths:
            out.append(
                encode_series(
                    times[pos : pos + ln].tolist(),
                    values[pos : pos + ln].tolist(),
                    int_optimized=int_optimized,
                    unit=Unit(default_unit),
                )
            )
            pos += ln
        return out
    if n_threads <= 0:
        n_threads = min(os.cpu_count() or 1, 16)
    cap = max(int(times.size * 16 + n * 16 + 1024), 4096)
    total, out_buf, offsets = _encode_batch_native(
        lib, times, values, lengths, default_unit, int_optimized, n_threads, cap
    )
    if total < 0:  # grow to the exact required size and retry once
        total, out_buf, offsets = _encode_batch_native(
            lib, times, values, lengths, default_unit, int_optimized, n_threads, -total
        )
    raw = out_buf.tobytes()
    return [raw[offsets[i] : offsets[i + 1]] for i in range(n)]


def prescan_batch(
    streams: list[bytes],
    k: int = 32,
    default_unit: int = 1,
    int_optimized: bool = True,
    n_threads: int = 0,
) -> list[list[dict]]:
    """Side-table prescan for N streams → per-series snapshot dict lists
    (same shape as ops.chunked.snapshot_stream)."""
    lib = load()
    if lib is None:
        from ..ops.chunked import snapshot_stream
        from ..utils.xtime import Unit

        return [
            snapshot_stream(s, k, int_optimized=int_optimized, default_unit=Unit(default_unit))
            for s in streams
        ]
    n = len(streams)
    if n == 0:
        return []
    data = b"".join(streams)
    offsets = np.zeros(n + 1, np.int64)
    for i, s in enumerate(streams):
        offsets[i + 1] = offsets[i] + len(s)
    max_len = max((len(s) for s in streams), default=0)
    # record lower bound ~3 bits, so snapshots per stream are bounded by this
    max_snaps = max((max_len * 8) // max(3 * k, 1) + 2, 2)
    buf = (_SnapRec * (n * max_snaps))()
    counts = np.zeros(n, np.int32)
    arr = np.frombuffer(data, np.uint8) if data else np.zeros(1, np.uint8)
    if n_threads <= 0:
        n_threads = min(os.cpu_count() or 1, 16)
    lib.m3tsz_prescan_batch(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int32(n),
        ctypes.c_int32(k),
        ctypes.c_int(default_unit),
        ctypes.c_int(1 if int_optimized else 0),
        buf,
        ctypes.c_int32(max_snaps),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int32(n_threads),
    )
    out: list[list[dict]] = []
    for i in range(n):
        total_bits = len(streams[i]) * 8
        per = []
        c = max(int(counts[i]), 0)
        for j in range(c):
            r = buf[i * max_snaps + j]
            per.append(
                dict(
                    off=r.off,
                    prev_time=r.prev_time,
                    prev_delta=r.prev_delta,
                    prev_float_bits=r.prev_float_bits,
                    prev_xor=r.prev_xor,
                    int_val=r.int_val,
                    time_unit=r.time_unit,
                    sig=r.sig,
                    mult=r.mult,
                    is_float=bool(r.is_float),
                    fast=bool(r.flags & 1),
                    fast_float=bool(r.flags & 2),
                    total_bits=total_bits,
                )
            )
        offs = [p["off"] for p in per] + [total_bits]
        for j, p in enumerate(per):
            p["span"] = offs[j + 1] - p["off"]
        out.append(per)
    return out


def pack_windowed_dense(
    ids: np.ndarray,
    times_nanos: np.ndarray,
    values: np.ndarray,
    window0_nanos: int,
    resolution_nanos: int,
    n_windows: int,
    n_series: int,
    n_threads: int = 0,
):
    """Fused window bucketing + dense [G, P] pack for the device rollup
    kernels (aggregator/kernels.py aggregate_dense): keys/torder, counts and
    the arrival-order-exact dense scatter in three memory-bound C++ passes.
    Returns (vals[G, P] f32, torder[G, P] i32, valid[G, P] bool).

    Falls back to the numpy path (kernels.window_keys + pack_dense_groups)
    when the native lib is unavailable. Reference hot loop:
    /root/reference/src/aggregator/aggregation/{counter,timer,gauge}.go."""
    lib = load()
    n = len(ids)
    n_groups = n_series * n_windows
    # the native kernel computes int32 group keys (m3tsz.cc m3agg_window_keys)
    # and m3agg_count indexes with them: past INT32_MAX the cast wraps
    # negative and the atomic fetch_add writes out of bounds — route
    # oversized grids through the int64-keyed numpy path instead
    if lib is None or n_groups > np.iinfo(np.int32).max:
        from ..aggregator.kernels import pack_dense_groups, window_keys

        keys, _, order = window_keys(
            np.asarray(ids), np.asarray(times_nanos), window0_nanos,
            resolution_nanos, n_windows,
        )
        return pack_dense_groups(keys, values, order, n_groups)
    if n_threads <= 0:
        n_threads = min(os.cpu_count() or 1, 16)
    ids = np.ascontiguousarray(ids, np.int64)
    times_nanos = np.ascontiguousarray(times_nanos, np.int64)
    values = np.ascontiguousarray(values, np.float32)
    keys = np.empty(n, np.int32)
    torder = np.empty(n, np.int32)
    lib.m3agg_window_keys(
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        times_nanos.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(n),
        ctypes.c_int64(window0_nanos),
        ctypes.c_int64(resolution_nanos),
        ctypes.c_int32(n_windows),
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        torder.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int32(n_threads),
    )
    counts = np.zeros(n_groups, np.int32)
    p = int(
        lib.m3agg_count(
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int64(n),
            ctypes.c_int64(n_groups),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(n_threads),
        )
    )
    p = max(p, 1)
    vals = np.empty((n_groups, p), np.float32)
    tor = np.empty((n_groups, p), np.int32)
    lib.m3agg_pack(
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        torder.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int64(n),
        ctypes.c_int64(n_groups),
        ctypes.c_int32(p),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        tor.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int32(n_threads),
    )
    # match the numpy fallback exactly: a NaN input value occupies a slot
    # but must be INVALID (stale markers etc. are dropped, not folded into
    # sum/min/max as NaN)
    valid = (np.arange(p, dtype=np.int32)[None, :] < counts[:, None]) & ~np.isnan(
        vals
    )
    return vals, tor, valid


def decode_batch(
    streams: list[bytes],
    default_unit: int = 1,
    int_optimized: bool = True,
    n_threads: int = 0,
    max_points: int | None = None,
    with_flags: bool = False,
):
    """Batch-decode N m3tsz streams → list of (times i64[n], values f64[n],
    units u8[n]) numpy triples. ~100x the pure-Python decoder; serves host
    paths that need plain points — shard reads, repair digests, the
    comparator, CPU benches. Annotations do not alter (t, v, u) decoding;
    with ``with_flags`` the return is (triples, flags u8[n]) where flag
    bit0 marks streams that DO carry annotations, so callers that must
    surface them (Datapoint.annotation) can re-decode those few through the
    Python iterator.

    Reference: the Go iterator's batch decode role
    (/root/reference/src/dbnode/encoding/m3tsz/iterator.go:64). Falls back
    to the Python decoder when the native lib is unavailable."""

    def _python_one(s):
        from ..codec.m3tsz import decode
        from ..utils.xtime import Unit

        dps = decode(s, int_optimized=int_optimized, default_unit=Unit(default_unit))
        return (
            np.asarray([d.timestamp for d in dps], np.int64),
            np.asarray([d.value for d in dps], np.float64),
            np.asarray([int(d.unit) for d in dps], np.uint8),
        )

    def _python_flags(s):
        from ..codec.m3tsz import decode
        from ..utils.xtime import Unit

        dps = decode(s, int_optimized=int_optimized, default_unit=Unit(default_unit))
        return 1 if any(d.annotation for d in dps) else 0

    lib = load()
    n = len(streams)
    if n == 0:
        return ([], np.zeros(0, np.uint8)) if with_flags else []
    if lib is None:
        triples = [_python_one(s) for s in streams]
        if with_flags:
            return triples, np.asarray([_python_flags(s) for s in streams], np.uint8)
        return triples
    data = b"".join(streams)
    offsets = np.zeros(n + 1, np.int64)
    for i, s in enumerate(streams):
        offsets[i + 1] = offsets[i] + len(s)
    arr = np.frombuffer(data, np.uint8) if data else np.zeros(1, np.uint8)
    # capacity: one datapoint per 2 encoded bits is unreachable by the
    # format (min ~3 bits/record), so bits//2 + 2 never overflows; callers
    # that know their block shape pass max_points to avoid page-fault cost
    # on oversized outputs
    cap = max_points or max(int(max(len(s) for s in streams)) * 4 + 2, 4)
    if n_threads <= 0:
        n_threads = min(os.cpu_count() or 1, 16)
    times = np.empty((n, cap), np.int64)
    values = np.empty((n, cap), np.float64)
    units = np.empty((n, cap), np.uint8)
    counts = np.zeros(n, np.int64)
    flags = np.zeros(n, np.uint8)
    failed = lib.m3tsz_decode_batch(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int32(n),
        ctypes.c_int(default_unit),
        ctypes.c_int(1 if int_optimized else 0),
        ctypes.c_int64(cap),
        times.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        units.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        flags.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_int32(n_threads),
    )
    if failed:
        if max_points is not None and any(c == -2 for c in counts):
            # caller's cap was too small somewhere: retry with the safe bound
            return decode_batch(
                streams, default_unit=default_unit, int_optimized=int_optimized,
                n_threads=n_threads, max_points=None, with_flags=with_flags,
            )
        bad = [i for i, c in enumerate(counts) if c < 0]
        raise ValueError(f"m3tsz decode failed for {len(bad)} streams (first: {bad[:3]})")
    triples = [
        (
            times[i, : counts[i]].copy(),
            values[i, : counts[i]].copy(),
            units[i, : counts[i]].copy(),
        )
        for i in range(n)
    ]
    return (triples, flags) if with_flags else triples


def encode_one(
    times: np.ndarray,
    values: np.ndarray,
    units: np.ndarray | None = None,
    default_unit: int = 1,
    int_optimized: bool = True,
) -> bytes | None:
    """Encode ONE series with optional per-point units via the native
    encoder (m3tsz_encode_series); None when the lib is unavailable (the
    caller uses the Python reference encoder). The buffer-bucket merge
    path (storage/series.py) is the hot consumer."""
    lib = load()
    if lib is None:
        return None
    times = np.ascontiguousarray(times, np.int64)
    values = np.ascontiguousarray(values, np.float64)
    n = len(times)
    if n == 0:
        return b""
    u_ptr = None
    if units is not None:
        units = np.ascontiguousarray(units, np.int32)
        u_ptr = units.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    cap = n * 16 + 1024
    for _ in range(2):
        out = np.zeros(cap, np.uint8)
        r = int(
            lib.m3tsz_encode_series(
                times.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                ctypes.c_int32(n),
                ctypes.c_int(default_unit),
                u_ptr,
                ctypes.c_int(1 if int_optimized else 0),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.c_int64(cap),
            )
        )
        if r >= 0:
            return out[:r].tobytes()
        if r == -1:
            return None  # encode error: let the python path raise properly
        cap = -r
    return None


def shard_batch(ids: list[bytes], num_shards: int) -> "np.ndarray | None":
    """murmur3-32 shard routing for a batch of series ids in one native
    call (sharding/shardset.go DefaultHashFn; parity with utils/hash.py).
    None when the lib is unavailable (callers hash per-id in Python)."""
    lib = load()
    if lib is None:
        return None
    n = len(ids)
    blob = b"".join(ids)
    offsets = np.zeros(n + 1, np.int64)
    for i, s in enumerate(ids):
        offsets[i + 1] = offsets[i] + len(s)
    arr = np.frombuffer(blob, np.uint8) if blob else np.zeros(1, np.uint8)
    out = np.empty(n, np.int32)
    lib.m3hash_shards(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int32(n),
        ctypes.c_int32(num_shards),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out
