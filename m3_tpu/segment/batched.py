"""Columnar batched-segment container — the unit shipped to device.

The reference hands query nodes compressed per-series segments
(ts.Segment via xio.BlockReader, /root/reference/src/dbnode/x/xio/). The TPU
framework instead batches N series' finalized M3TSZ streams into dense arrays:

- ``words``: uint32[S, W] — each stream's bytes packed big-endian into 32-bit
  words (bit 0 of the stream is the MSB of word 0), zero-padded to the batch
  max length. MSB-first packing matches the OStream bit order exactly, so the
  device bit cursor is just a flat bit index.
- ``num_bits``: int32[S] — valid bits per series.

This is the array-of-structure-of-arrays equivalent of a []ts.Segment and the
input to ops.decode.decode_batched.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np


@dataclass
class BatchedSegments:
    words: np.ndarray  # uint32[S, W]
    num_bits: np.ndarray  # int32[S]

    @property
    def num_series(self) -> int:
        return self.words.shape[0]

    @property
    def num_words(self) -> int:
        return self.words.shape[1]

    @staticmethod
    def from_streams(streams: Sequence[bytes], pad_words: int | None = None) -> "BatchedSegments":
        """Pack finalized M3TSZ streams into a dense word matrix."""
        n = len(streams)
        max_len = max((len(s) for s in streams), default=0)
        w = (max_len + 3) // 4
        if pad_words is not None:
            w = max(w, pad_words)
        # Pad W so the decoder's 3-word window fetch never needs bounds checks
        # beyond index clamping.
        w += 2
        words = np.zeros((n, w), dtype=np.uint32)
        num_bits = np.zeros((n,), dtype=np.int32)
        for i, s in enumerate(streams):
            num_bits[i] = len(s) * 8
            if not s:
                continue
            padded = s + b"\x00" * (-len(s) % 4)
            words[i, : len(padded) // 4] = np.frombuffer(padded, dtype=">u4").astype(np.uint32)
        return BatchedSegments(words=words, num_bits=num_bits)

    def initial_units(self, default_unit=None) -> np.ndarray:
        """Per-series initial time-unit codes for the device decoder.

        Mirrors initialTimeUnit (m3tsz/timestamp_encoder.go:208-219): the
        default unit applies only when the stream's first 64-bit timestamp is
        an exact multiple of it, else the stream starts unitless (None) and
        carries a time-unit marker.
        """
        from ..utils.xtime import Unit

        if default_unit is None:
            default_unit = Unit.SECOND
        if self.num_words < 2:
            return np.zeros((self.num_series,), dtype=np.int32)
        nt = (self.words[:, 0].astype(np.uint64) << np.uint64(32)) | self.words[:, 1].astype(
            np.uint64
        )
        aligned = (nt % np.uint64(default_unit.nanos())) == 0
        has_first = self.num_bits >= 64
        return np.where(aligned & has_first, np.int32(default_unit), np.int32(0))

    def stream(self, i: int) -> bytes:
        """Recover series i's stream bytes (for tests / host round trips)."""
        nbytes = int(self.num_bits[i]) // 8
        raw = self.words[i].astype(">u4").tobytes()
        return raw[:nbytes]
