"""Full benchmark suite: BASELINE.md configs 1-5, the mixed-workload bench,
and the scan p50 latency — the honest numbers the round-3 verdict asked for
(bench.py stays the driver's single headline line; this writes PERF_r04.json).

Run:  python bench_suite.py [--configs 1,2,3,4,5,mixed,scan] [--series N]

Each config prints one BENCH-style JSON line and all records land in
PERF_r04.json. On CPU the workloads shrink (sanity only — real numbers come
from the TPU chip).
"""

from __future__ import annotations

import argparse
import functools
import json
import time

import numpy as np

NANOS = 1_000_000_000
NORTH_STAR = 10e9
T0 = 1_600_000_000 * NANOS


def _rec(metric, value, unit, **extra):
    rec = {
        "metric": metric,
        "value": round(float(value), 4),
        "unit": unit,
        "vs_baseline": round(float(value) / NORTH_STAR, 6)
        if unit == "datapoints/s"
        else None,
        **extra,
    }
    print(json.dumps(rec), flush=True)
    return rec


def _fetch(out):
    """Force a REAL device→host sync by materializing one scalar (the axon
    tunnel's block_until_ready can return early; a data fetch cannot).
    Indexes a single element so big outputs don't ride the tunnel."""
    leaf = out
    if hasattr(out, "total_count"):
        leaf = out.total_count
    elif isinstance(out, (tuple, list)):
        leaf = out[0]
    if getattr(leaf, "ndim", 0):
        leaf = leaf[(0,) * leaf.ndim]
    return float(leaf)


def _timeit(fn, args, iters=10):
    """Self-validating timing: pipelined (block-at-end, amortizes the
    tunnel's ~10ms dispatch rtt) cross-checked against synchronous
    fetch-per-iter. A pipelined number >20x faster than sync means the
    block didn't block (observed on the axon tunnel for some shapes) —
    report sync instead."""
    import jax

    out = fn(args)
    jax.block_until_ready(out)
    _fetch(out)
    n_sync = max(3, iters // 3)
    t0 = time.perf_counter()
    for _ in range(n_sync):
        _fetch(fn(args))
    dt_sync = (time.perf_counter() - t0) / n_sync
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(args)
    jax.block_until_ready(out)
    dt_pipe = (time.perf_counter() - t0) / iters
    dt = dt_sync if dt_pipe < dt_sync / 20 else dt_pipe
    return dt, out


def _timeit_chain(scalar_step, args, k_lo=4, k_hi=16, reps=3):
    """Per-application time of a kernel via the K-slope method.

    ``scalar_step(carry, *args) -> f32 scalar`` applies the kernel once with
    a data dependency on ``carry`` (so XLA cannot CSE/DCE the chain). We jit
    a lax.scan of K applications, synchronously time (result fetch) K_hi and
    K_lo dispatches, and divide the difference by (K_hi - K_lo): fixed costs
    — the tunnel's ~10ms dispatch RTT, result transfer — cancel exactly.
    This measures sustained throughput, which is what a streaming flush/query
    pipeline sees; sub-ms kernels are otherwise swamped by dispatch latency
    (the r04 config3/config4 numbers were RTT-bound, not compute-bound).
    Falls back to plain sync timing if the slope is non-positive (noise)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def chained(k):
        @jax.jit
        def f(*a):
            def body(c, _):
                return scalar_step(c, *a) * 1e-30, None

            c, _ = lax.scan(body, jnp.float32(0), None, length=k)
            return c

        return f

    f_lo, f_hi = chained(k_lo), chained(k_hi)
    _fetch(f_lo(*args))
    _fetch(f_hi(*args))  # compile + residency settle
    lo_ts, hi_ts = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        _fetch(f_lo(*args))
        lo_ts.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        _fetch(f_hi(*args))
        hi_ts.append(time.perf_counter() - t0)
    slope = (np.median(hi_ts) - np.median(lo_ts)) / (k_hi - k_lo)
    if slope <= 0:  # noise floor: report the conservative sync latency
        return np.median(hi_ts) / k_hi
    return slope


def _latencies(fn, args, iters=20):
    for _ in range(4):  # compile + argument residency settle
        _fetch(fn(args))
    lats = []
    for _ in range(iters):
        t0 = time.perf_counter()
        _fetch(fn(args))
        lats.append(time.perf_counter() - t0)
    return np.asarray(lats)


# --- config 1: CPU codec round trip (m3tsz_benchmark_test.go role) ---


def bench_config1():
    from m3_tpu import native
    from m3_tpu.codec.m3tsz import decode
    from m3_tpu.utils.synthetic import synthetic_streams

    streams = synthetic_streams(1000, 720, seed=1)
    nbytes = sum(map(len, streams))
    npts = 1000 * 720
    # native batch decoder (native/m3tsz.cc m3tsz_decode_batch — the Go
    # iterator's role, single-core number reported for /core parity)
    native.decode_batch(streams[:4])  # lazy build + warm
    t0 = time.perf_counter()
    out = native.decode_batch(streams, n_threads=1, max_points=720)
    dt = time.perf_counter() - t0
    assert sum(len(t) for t, _, _ in out) == npts
    # pure-Python reference decoder (annotation-capable fallback)
    t0 = time.perf_counter()
    total = sum(len(decode(s)) for s in streams[:50])
    dt_py = (time.perf_counter() - t0) * (len(streams) / 50)
    assert total == 50 * 720
    return _rec(
        "config1_cpu_decode_roundtrip",
        npts / dt,
        "datapoints/s",
        bytes_per_datapoint=round(nbytes / npts, 3),
        series=1000,
        python_decode_dps=round(npts / dt_py, 1),
    )


# --- config 2: S x 720 packed decode+aggregate (the headline shape) ---


def _packed_fn(batch, order="c"):
    import jax

    from m3_tpu.ops import fused
    from m3_tpu.parallel.scan import chunked_scan_aggregate_packed

    packed = fused.pack_lane_inputs(batch, order=order)
    w4 = jax.device_put(packed.windows4)
    l4 = jax.device_put(packed.lanes4)
    tf = jax.device_put(packed.tile_flags)
    fn0 = jax.jit(
        functools.partial(
            chunked_scan_aggregate_packed,
            n=packed.n,
            s=batch.num_series,
            c=batch.num_chunks,
            k=batch.k,
            lane_order=packed.order,
            # cross-series totals are order-independent; per-series arrays
            # come back in sorted order and unpermute on host via inv
            unpermute_series=False,
        )
    )
    fn = lambda _: fn0(w4, l4, tf)
    return fn, packed


def _jnp_fn(batch):
    import jax

    from m3_tpu.parallel.scan import chunked_device_args, chunked_scan_aggregate_fused

    args = chunked_device_args(batch)
    fn0 = jax.jit(
        functools.partial(
            chunked_scan_aggregate_fused,
            s=batch.num_series,
            c=batch.num_chunks,
            k=batch.k,
        )
    )
    return lambda _: fn0(args)


def _build(streams, n_series, k=24):
    from m3_tpu.ops.chunked import build_chunked, tile_chunked

    return tile_chunked(build_chunked(streams, k=k), n_series)


def bench_config2(n_series, on_tpu):
    from m3_tpu.utils.synthetic import synthetic_streams

    batch = _build(synthetic_streams(64, 720, seed=3), n_series)
    fn = _packed_fn(batch)[0] if on_tpu else _jnp_fn(batch)
    dt, out = _timeit(fn, None)
    pts = int(out.total_count)
    return _rec(
        "config2_decode_aggregate",
        pts / dt,
        "datapoints/s",
        series=n_series,
        points=720,
    )


def bench_mixed(n_series, on_tpu):
    """Mixed workload: >=30% float-mode + counters + time-unit changes +
    annotations + varied gauge entropy, interleaved (not 64 tiled uniques).
    Sorted lane packing routes the fast majority to the specialized body."""
    from m3_tpu.utils.synthetic import synthetic_mixed_streams

    batch = _build(synthetic_mixed_streams(256, 720, seed=11), n_series)
    fast_frac = float(np.asarray(batch.fast).mean())
    ff_frac = float(np.asarray(batch.fast_float).mean())
    int_tiles = float_tiles = 0.0
    if on_tpu:
        fn, packed = _packed_fn(batch, order="sorted")
        int_tiles = float((packed.tile_flags == 1).mean())
        float_tiles = float((packed.tile_flags == 2).mean())
    else:
        fn = _jnp_fn(batch)
    dt, out = _timeit(fn, None)
    pts = int(out.total_count)
    return _rec(
        "mixed_workload_decode_aggregate",
        pts / dt,
        "datapoints/s",
        series=n_series,
        fast_lane_fraction=round(fast_frac, 4),
        fast_float_lane_fraction=round(ff_frac, 4),
        int_tile_fraction=round(int_tiles, 4),
        float_tile_fraction=round(float_tiles, 4),
        composition="30% float, 8% counter, 5% tu-change, 2% annotation, 55% gauge",
    )


def bench_scan_p50(n_series, on_tpu):
    """1M->50M scan p50: per-dispatch latency of the full decode+aggregate
    at the given series count (the second half of the north-star metric)."""
    from m3_tpu.utils.synthetic import synthetic_streams

    batch = _build(synthetic_streams(64, 720, seed=3), n_series)
    fn = _packed_fn(batch)[0] if on_tpu else _jnp_fn(batch)
    lats = _latencies(fn, None)
    return _rec(
        "scan_latency_p50",
        float(np.percentile(lats, 50)),
        "seconds",
        series=n_series,
        p90=round(float(np.percentile(lats, 90)), 6),
        p99=round(float(np.percentile(lats, 99)), 6),
    )


# --- config 3: temporal functions over a decoded block ---


def bench_config3(n_series):
    import jax
    import jax.numpy as jnp

    from m3_tpu.query.functions import temporal

    t = 720
    rng = np.random.default_rng(0)
    vals = rng.normal(100, 10, (n_series, t)).astype(np.float32)
    vals[rng.random((n_series, t)) < 0.01] = np.nan  # missing samples
    x = jax.device_put(jnp.asarray(vals))
    window = 7  # 1m range at 10s step

    from m3_tpu.query.functions.temporal_fused import fused_temporal

    def step(carry, v):
        r, a = fused_temporal(
            v + carry, window, 10.0, ("rate", "avg_over_time")
        )
        return jnp.nansum(r) + jnp.nansum(a)

    dt = _timeit_chain(step, (x,))
    # two functions over S*T points each
    return _rec(
        "config3_temporal_functions",
        2 * n_series * t / dt,
        "datapoints/s",
        series=n_series,
        functions="rate+avg_over_time",
    )


# --- config 4: 10M active series 10s->1m rollups ---


def bench_config4(n_series):
    import jax
    import jax.numpy as jnp

    from m3_tpu import native
    from m3_tpu.aggregator.kernels import aggregate_dense, dense_quantiles

    per = 6  # datapoints per series in the 1m window (10s resolution)
    n = n_series * per
    rng = np.random.default_rng(2)
    ids = np.repeat(np.arange(n_series, dtype=np.int64), per)
    times = T0 + np.tile((np.arange(per) * 10 * NANOS), n_series) + rng.integers(
        0, 10 * NANOS, n
    )
    values = rng.lognormal(0, 1, n).astype(np.float32)
    # fused native densify (m3agg_* in native/m3tsz.cc): window bucketing +
    # counts + arrival-order-exact dense scatter, memory-bound C++ passes
    t0 = time.perf_counter()
    dv, dt_, dvalid = native.pack_windowed_dense(
        ids, times, values, T0, 60 * NANOS, 1, n_series
    )
    pack_s = time.perf_counter() - t0
    dvd = jax.device_put(dv)
    dtd = jax.device_put(dt_)
    dvld = jax.device_put(dvalid)

    def agg_step(carry, vals, torder, valid):
        out = aggregate_dense(vals + carry, torder, valid)
        return out.sum.sum() + out.last.sum() + out.min.sum() + out.max.sum()

    dt_agg = _timeit_chain(agg_step, (dvd, dtd, dvld))

    # timer quantiles on a 10% timer population (p50/p95/p99)
    n_t = max(n_series // 10, 1)
    vq = jax.device_put(dv[:n_t])
    vlq = jax.device_put(dvalid[:n_t])

    def q_step(carry, vals, valid):
        return jnp.nansum(dense_quantiles(vals + carry, valid, qs=(0.5, 0.95, 0.99)))

    # the timer slice is 10x smaller: longer chains keep the slope above the
    # dispatch-jitter noise floor
    dt_q = _timeit_chain(q_step, (vq, vlq), k_lo=32, k_hi=256)

    tmask = n_t * per
    total_dps = n + tmask
    return _rec(
        "config4_rollup_10s_to_1m",
        total_dps / (dt_agg + dt_q),
        "datapoints/s",
        active_series=n_series,
        agg_dps=round(n / dt_agg, 1),
        timer_quantile_dps=round(tmask / dt_q, 1),
        host_densify_s=round(pack_s, 3),
    )


# --- config 5: regexp index query -> decode -> aggregate (fan-out) ---


def bench_config5(n_series, on_tpu):
    from m3_tpu.index.query import RegexpQuery, search_segment
    from m3_tpu.index.segment import Document, MutableSegment
    from m3_tpu.ops.chunked import select_series
    from m3_tpu.utils.synthetic import synthetic_streams

    # index S series: name=metric_{i%100}, dc, host
    seg = MutableSegment()
    t_ix0 = time.perf_counter()
    for i in range(n_series):
        seg.insert(
            Document(
                id=str(i).encode(),
                fields=(
                    (b"name", f"metric_{i % 100}".encode()),
                    (b"dc", f"dc{i % 4}".encode()),
                ),
            )
        )
    sealed = seg.seal()
    index_build_s = time.perf_counter() - t_ix0

    q = RegexpQuery(b"name", b"metric_1[0-9]")  # ~10% of series
    t_q0 = time.perf_counter()
    postings = search_segment(sealed, q)
    query_s = time.perf_counter() - t_q0
    sel = np.asarray(postings, np.int64)

    # the synthetic population tiles 64 unique streams across n_series, so
    # selecting from the tiled batch == selecting (i % 64) from the base —
    # composing the two skips materializing a multi-GB copy of REPEATED
    # data that no real deployment would hold (real series are gathered
    # from their own storage); the gather below still moves the full
    # matched-series byte volume
    base = _build(synthetic_streams(64, 720, seed=3), 64)
    t_s0 = time.perf_counter()
    sub = select_series(base, sel % 64)
    select_s = time.perf_counter() - t_s0

    fn = _packed_fn(sub)[0] if on_tpu else _jnp_fn(sub)
    dt, out = _timeit(fn, None)
    pts = int(out.total_count)
    return _rec(
        "config5_regexp_fanout_decode_aggregate",
        pts / dt,
        "datapoints/s",
        indexed_series=n_series,
        matched_series=int(sel.size),
        index_query_ms=round(query_s * 1e3, 2),
        index_build_s=round(index_build_s, 2),
        select_pack_s=round(select_s, 2),
    )


def bench_multitenant(rate=400.0, duration=5.0):
    """Mixed multi-tenant read+write bench (ROADMAP open item 3's success
    metric): an in-process coordinator behind its real HTTP surface, a
    two-tenant open-loop fixed-rate workload (services/loadgen.py
    --tenants mode; ticks the loop can't take are counted, not absorbed —
    no coordinated omission), reporting sustained QPS and per-tenant
    p50/p95/p99."""
    import argparse

    from m3_tpu.services import loadgen
    from m3_tpu.services.coordinator import Coordinator, serve

    coord = Coordinator()
    srv, port = serve(coord, 0)
    try:
        args = argparse.Namespace(
            node="", coordinator=f"127.0.0.1:{port}", aggregator="",
            namespace="default", series=200, rate=rate, duration=duration,
            workers=8, batch=10, read_fraction=0.3, series_offset=0,
            listen=None, agents="", tenants="alpha:3,beta:1",
        )
        out = loadgen.run_multitenant(
            args, loadgen.make_tenant_client_factory(args)
        )
    finally:
        srv.shutdown()
        coord.db.close()
    return _rec(
        "multitenant_sustained_qps",
        out["sustained_ops_per_sec"],
        "ops/s",
        target_ops_per_sec=out["target_ops_per_sec"],
        missed_ticks=out["missed_ticks"],
        errors=out["errors"],
        rejected=out["rejected"],
        per_tenant={
            name: {
                k: t[k]
                for k in ("ops_per_sec", "p50_ms", "p95_ms", "p99_ms")
            }
            for name, t in out["tenants"].items()
        },
    )


def bench_hedging(reads=150, delay=0.4, delay_prob=0.12):
    """Hedging column for the tenants row (PR 14): the SAME 3-replica
    tagged-read workload against a cluster whose node1 read path
    straggles (seeded jittered lognormal delay on fetch_tagged),
    measured closed-loop with hedged backup requests OFF then ON. An
    unhedged read that draws the straggler pays the full
    ``straggler_grace`` wait; a hedged one gets a backup twin at the
    p95 trigger and returns as soon as every host is settled. The
    headline is p99_ratio (hedged/unhedged); hedge counters prove the
    backup path actually carried the wins."""
    from m3_tpu.index.query import term
    from m3_tpu.net.faults import FaultPlan, FaultRule
    from m3_tpu.testing.cluster import LocalCluster
    from m3_tpu.testing.faults import wrap_nodes
    from m3_tpu.utils.instrument import DEFAULT as METRICS

    def hedge_counter(kind):
        fam = METRICS.collect().get(f"m3tpu_session_hedges_{kind}_total")
        return sum(c["value"] for c in fam["children"]) if fam else 0.0

    nanos = 1_000_000_000
    t0 = 1_600_000_000 * nanos
    plan = FaultPlan(
        [FaultRule(op="fetch_tagged", peer="node1", delay=delay,
                   delay_prob=delay_prob, jitter=0.1,
                   delay_dist="lognormal")],
        seed=11,
    )
    cluster = LocalCluster(num_nodes=3, num_shards=4, replica_factor=3)
    modes = {}
    issued = won = 0.0
    try:
        seed_session = cluster.session()
        for i in range(16):
            tags = ((b"__name__", b"bench_hedge"), (b"i", b"%d" % i))
            seed_session.write_tagged(tags, t0 + i * nanos, float(i))
        seed_session.close()
        q = term(b"__name__", b"bench_hedge")
        for mode, hedged in (("unhedged", False), ("hedged", True)):
            s = cluster.session()
            s.nodes = wrap_nodes(s.nodes, plan)
            s.hedge_enabled = hedged
            i0, w0 = hedge_counter("issued"), hedge_counter("won")
            lats = []
            bench_t0 = time.perf_counter()
            for _ in range(reads):
                r0 = time.perf_counter()
                res = s.fetch_tagged(q, t0 - 1, t0 + 3600 * nanos)
                lats.append(time.perf_counter() - r0)
                assert len(list(res)) == 16
            elapsed = time.perf_counter() - bench_t0
            lats.sort()
            modes[mode] = {
                "reads_per_sec": round(reads / elapsed, 1),
                "p50_ms": round(lats[len(lats) // 2] * 1e3, 2),
                "p99_ms": round(lats[int(len(lats) * 0.99) - 1] * 1e3, 2),
            }
            if hedged:
                issued = hedge_counter("issued") - i0
                won = hedge_counter("won") - w0
            s.close()
    finally:
        import shutil

        shutil.rmtree(cluster.base_dir, ignore_errors=True)
    return _rec(
        "hedged_read_tail_latency",
        round(modes["hedged"]["p99_ms"] / max(modes["unhedged"]["p99_ms"], 1e-9), 3),
        "p99 ratio (hedged/unhedged)",
        straggler={"peer": "node1", "delay_s": delay,
                   "delay_prob": delay_prob, "dist": "lognormal"},
        hedges_issued=issued,
        hedges_won=won,
        **modes,
    )


def bench_pipeline(n_series=None, on_tpu=False):
    """Staged-vs-fused device-query-plan sweep (query/plan.py): an
    in-process Database (resident pool + device index) seeded with the
    dispatch-bound temporal shape — MANY short series, the monitoring
    fleet profile where per-stage host overhead dominates device compute
    — then the SAME ``rate(metric{job=~...}[w])`` query timed warm
    through the fused one-dispatch plan and the staged executor
    (plan.force_staged). Plan-compile/build time is excluded from the
    steady-state percentiles and reported separately
    (``plan_warmup_ms``). Acceptance: fused p50 <= 0.5x staged p50 on
    CPU, with per-query profiled dispatch counts reported for both."""
    import statistics
    import tempfile
    import time as _time

    import numpy as _np

    from m3_tpu.index.device.store import IndexDeviceOptions
    from m3_tpu.query import plan as qplan
    from m3_tpu.query import stats as qstats
    from m3_tpu.query.engine import Engine
    from m3_tpu.query.m3_storage import M3Storage
    from m3_tpu.resident.pool import ResidentOptions
    from m3_tpu.rules.rules import encode_tags_id
    from m3_tpu.storage.database import Database, NamespaceOptions

    n_series = n_series or (65536 if on_tpu else 8192)
    n_points = 16
    NANOS_ = 1_000_000_000
    t0 = 1_600_000_000 * NANOS_
    step = 10 * NANOS_
    db = Database(
        tempfile.mkdtemp(prefix="m3tpu-bench-pipe-"), num_shards=4,
        commitlog_enabled=False,
        resident_options=ResidentOptions(max_bytes=256 << 20),
        index_device_options=IndexDeviceOptions(max_bytes=256 << 20),
    )
    db.create_namespace("bench", NamespaceOptions(block_size_nanos=3600 * NANOS_))
    rng = _np.random.default_rng(0)
    for i in range(n_series):
        tags = ((b"__name__", b"bp"), (b"job", b"app%d" % (i % 4)),
                (b"s", b"%06d" % i))
        sid = encode_tags_id(tags)
        db.write_tagged("bench", tags, t0, float(i % 7))
        db.write_batch(
            "bench",
            [(sid, t0 + (j + 1) * step,
              float(rng.integers(0, 50)) / 4.0) for j in range(n_points - 1)],
        )
    db.flush("bench", t0 + 4 * 3600 * NANOS_)
    eng = Engine(M3Storage(db, "bench"))
    query = 'rate(bp{job=~"app.*"}[2m])'
    span = (t0 + 30 * NANOS_, t0 + (n_points - 1) * step, 30 * NANOS_)

    def run(staged: bool):
        st = qstats.start("bench")
        try:
            if staged:
                with qplan.force_staged():
                    eng.query_range(query, *span)
            else:
                eng.query_range(query, *span)
        finally:
            qstats.finish(st, 0.0)
        return st

    # warmup: plan build + every jit compile on BOTH paths, reported
    # apart from steady state
    w0 = _time.perf_counter()
    run(staged=False)
    plan_warmup_s = _time.perf_counter() - w0
    w0 = _time.perf_counter()
    run(staged=True)
    staged_warmup_s = _time.perf_counter() - w0

    def p50(staged: bool, iters=9):
        ts = []
        st = None
        for _ in range(iters):
            a = _time.perf_counter()
            st = run(staged)
            ts.append(_time.perf_counter() - a)
        return statistics.median(ts), st

    fused_p50, fused_st = p50(staged=False)
    staged_p50, staged_st = p50(staged=True)
    db.close()
    return _rec(
        "pipeline_fused_vs_staged",
        staged_p50 / max(fused_p50, 1e-12),
        "speedup",
        series=n_series,
        points=n_points,
        fused_p50_ms=round(fused_p50 * 1e3, 3),
        staged_p50_ms=round(staged_p50 * 1e3, 3),
        ratio=round(fused_p50 / staged_p50, 4),
        fused_dispatches=fused_st.device_dispatches,
        staged_dispatches=staged_st.device_dispatches,
        plan_hits=fused_st.plan_hits,
        plan_warmup_ms=round(plan_warmup_s * 1e3, 1),
        staged_warmup_ms=round(staged_warmup_s * 1e3, 1),
    )


def bench_ingest(on_tpu):
    """Device ingest suite (BENCH_r06 — the write-path twin of bench.py's
    read headline). Three records:

    1. ``ingest_device_write_plane`` (headline): sustained writes/s into
       the per-shard (series_lane, slot) column planes, device syncs
       riding along at the default IngestOptions.sync_batch cadence —
       the client-visible write plane, the apples-to-apples twin of
       PROFILE.md's 291k writes/s/core host BufferBucket ceiling (both
       exclude seal-time encode, which is lazy on both paths).
    2. ``ingest_encode_seal_kernel``: the seal-time chunk-parallel
       m3tsz encode (ops/encode.py) in datapoints/s.
    3. ``ingest_born_resident_seal``: end-to-end Database write->flush
       through device ingest — proves zero upload bytes on the device
       admissions while reporting the full-path rate.
    """
    import tempfile

    from m3_tpu.ingest import IngestOptions
    from m3_tpu.ingest.buffer import ColumnWriteBuffer
    from m3_tpu.ops import encode as dev_encode
    from m3_tpu.utils.instrument import Registry

    HOST_CEILING = 291_000.0  # writes/s/core, PROFILE.md round 5
    rng = np.random.default_rng(21)

    # --- 1) write plane: sustained append+sync ---
    B = 16384
    lanes = 8192 if on_tpu else 2048
    iters = 120 if on_tpu else 60
    opts = IngestOptions(lanes=lanes, slots=1024, sync_batch=B)
    buf = ColumnWriteBuffer(opts, 2 * 3600 * NANOS, registry=Registry("bi_"))
    sids = [b"s%05d" % (i % lanes) for i in range(B)]
    vals = (np.arange(B, dtype=np.float64) % 97) / 4.0
    units = np.ones(B, np.int8)
    base = (np.arange(B) // lanes).astype(np.int64)
    per = B // lanes
    buf.append_batch(sids, T0 + base * NANOS, vals, units)
    buf.sync()  # jit compile + plane residency settle
    t0 = time.perf_counter()
    n = 0
    for k in range(iters):
        ts = T0 + (base + per * (k + 1)) * NANOS
        buf.append_batch(sids, ts, vals, units)
        n += B
    dt = time.perf_counter() - t0
    assert buf.spills == dict.fromkeys(buf.spills, 0), buf.spills
    plane_rec = _rec(
        "ingest_device_write_plane",
        n / dt,
        "writes/s",
        vs_host_ceiling=round(n / dt / HOST_CEILING, 2),
        batch=B,
        lanes=lanes,
        device_syncs=buf.device_syncs,
        device_sync_bytes=buf.device_sync_bytes,
    )

    # --- 2) seal-time batched encode kernel ---
    M, N = (4096, 720) if on_tpu else (512, 720)
    enc_lanes = []
    for m in range(M):
        t = T0 + np.cumsum(rng.integers(1, 30, N)).astype(np.int64) * NANOS
        v = (
            rng.integers(-5000, 5000, N).astype(np.float64)
            if m % 2
            else rng.normal(0, 10, N)
        )
        enc_lanes.append((t, v))
    kinds = [
        dev_encode.classify_lane(t, v, np.ones(N, np.int8)).kind
        for t, v in enc_lanes
    ]
    dev_encode.encode_lanes(enc_lanes, kinds, k=32)  # compile warm
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        res = dev_encode.encode_lanes(enc_lanes, kinds, k=32)
    dt_enc = (time.perf_counter() - t0) / reps
    enc_rec = _rec(
        "ingest_encode_seal_kernel",
        M * N / dt_enc,
        "datapoints/s",
        lanes=M,
        points=N,
        bytes_per_datapoint=round(float(res.nbytes.sum()) / (M * N), 3),
    )

    # --- 3) end-to-end born-resident seal ---
    from m3_tpu.resident.pool import ResidentOptions
    from m3_tpu.storage.database import Database, NamespaceOptions

    bsz = 2 * 3600 * NANOS
    S, P = (4096, 128) if on_tpu else (512, 64)
    db = Database(
        tempfile.mkdtemp(prefix="m3tpu-bench-ingest-"),
        num_shards=4,
        commitlog_enabled=False,
        resident_options=ResidentOptions(enabled=True, max_bytes=256 << 20),
        ingest_options=IngestOptions(),
    )
    db.create_namespace("bench", NamespaceOptions(block_size_nanos=bsz))
    db.bootstrapped = True
    entries = []
    for s in range(S):
        sid = b"ser%05d" % s
        for p in range(P):
            entries.append((sid, bsz + (p * 20 + s % 17) * NANOS, float(s % 100)))
    t0 = time.perf_counter()
    db.write_batch("bench", entries)
    dt_w = time.perf_counter() - t0
    t0 = time.perf_counter()
    db.flush("bench", 2 * bsz)
    dt_f = time.perf_counter() - t0
    st = db.resident_pool.stats()
    db.close()
    npts = S * P
    seal_rec = _rec(
        "ingest_born_resident_seal",
        npts / (dt_w + dt_f),
        "writes/s",
        series=S,
        points=P,
        write_s=round(dt_w, 3),
        seal_s=round(dt_f, 3),
        device_admissions=st["device_admissions"],
        admissions=st["admissions"],
        upload_bytes=st["upload_bytes"],
        side_stage_bytes=st["ingest_side_stage_bytes"],
    )
    assert st["upload_bytes"] == 0, st
    assert st["device_admissions"] == st["admissions"] > 0, st
    return [plane_rec, enc_rec, seal_rec]


def bench_compression(n_series=2000, n_points=720):
    """bytes/datapoint on a PRODUCTION-LIKE trace, next to the reference's
    1.45 bytes/dp production claim (docs/m3db/architecture/engine.md:11).
    Composition modeled on a typical Prometheus scrape: regular 10s
    timestamps; step-y monotone request counters; low-cardinality gauges
    that mostly repeat (memory/queue sizes); one-decimal utilization
    gauges; a tail of higher-entropy latency floats."""
    from m3_tpu import native

    rng = np.random.default_rng(9)
    times = (T0 + np.arange(n_points) * 10 * NANOS).astype(np.int64)
    all_t, all_v, lens = [], [], []
    comp = {"counter": 0.4, "repeat_gauge": 0.3, "decimal_gauge": 0.2, "latency": 0.1}
    for i in range(n_series):
        r = i / n_series
        if r < comp["counter"]:
            # ~5 req/s with bursts; cumulative counter
            vals = np.cumsum(rng.poisson(50, n_points)).astype(float)
        elif r < comp["counter"] + comp["repeat_gauge"]:
            # changes rarely (queue depth, memory pages)
            base = float(rng.integers(100, 10000))
            steps = rng.choice([0, 0, 0, 0, 0, 0, 0, 1, -1], n_points)
            vals = base + np.cumsum(steps).astype(float)
        elif r < comp["counter"] + comp["repeat_gauge"] + comp["decimal_gauge"]:
            # one-decimal utilization percentage
            vals = np.round(rng.normal(55, 6, n_points), 1)
        else:
            # latency seconds, 3 decimals
            vals = np.round(rng.lognormal(-3, 0.4, n_points), 3)
        all_t.append(times)
        all_v.append(vals)
        lens.append(n_points)
    streams = native.encode_batch(
        np.concatenate(all_t), np.concatenate(all_v), np.asarray(lens, np.int32)
    )
    nbytes = sum(map(len, streams))
    npts = n_series * n_points
    return _rec(
        "compression_production_trace",
        nbytes / npts,
        "bytes/datapoint",
        series=n_series,
        reference_production_claim=1.45,
        composition="40% counters, 30% repeat gauges, 20% 1-decimal gauges, 10% latency",
    )


def bench_index(n_series, tmpdir="/tmp/m3tpu-index-bench"):
    """Index-at-scale microbench: build an n_series namespace index, persist
    to the mmap segment format, reopen zero-copy, and serve term + regexp
    queries (segment/fst/segment.go role + postings_list_cache.go)."""
    import shutil

    from m3_tpu.index.disk_segment import DiskSegment
    from m3_tpu.index.ns_index import NamespaceIndex
    from m3_tpu.index.query import regexp as regexp_q
    from m3_tpu.index.query import term as term_q

    HOUR = 3600 * NANOS
    shutil.rmtree(tmpdir, ignore_errors=True)
    ix = NamespaceIndex(block_size_nanos=HOUR)
    t0 = time.perf_counter()
    batch = [
        (
            f"s{i}".encode(),
            (
                (b"dc", b"dc%d" % (i % 4)),
                (b"host", b"h%d" % (i % 50021)),
                (b"name", b"metric_%d" % (i % 100)),
            ),
            T0,
        )
        for i in range(n_series)
    ]
    ix.write_batch(batch)
    build_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    ix.persist_before(tmpdir, "bench", T0 + 2 * HOUR)
    persist_s = time.perf_counter() - t0

    ix2 = NamespaceIndex(block_size_nanos=HOUR)
    t0 = time.perf_counter()
    ix2.load_persisted(tmpdir, "bench")
    open_s = time.perf_counter() - t0

    def lat(q, iters=5):
        out = []
        n = 0
        for _ in range(iters):
            t0 = time.perf_counter()
            r = ix2.query(q, T0 - HOUR, T0 + HOUR)
            out.append(time.perf_counter() - t0)
            n = len(r.docs)
        return out, n

    term_lats, term_n = lat(term_q(b"name", b"metric_42"))
    re_lats, re_n = lat(regexp_q(b"name", b"metric_1[0-9]"))
    # query results are lazy (index/query.py MatchedDocs); report the
    # full-materialization and ids-only costs separately so the latency
    # numbers above can't hide per-doc decode work downstream would pay
    r = ix2.query(regexp_q(b"name", b"metric_1[0-9]"), T0 - HOUR, T0 + HOUR)
    t0 = time.perf_counter()
    n_mat = len(list(r.docs))
    mat_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    ids = r.docs.ids() if hasattr(r.docs, "ids") else [d.id for d in r.docs]
    ids_s = time.perf_counter() - t0
    assert n_mat == len(ids) == re_n
    shutil.rmtree(tmpdir, ignore_errors=True)
    return _rec(
        "index_5m_mmap_segment",
        n_series / build_s,
        "docs_indexed/s",
        series=n_series,
        persist_s=round(persist_s, 2),
        mmap_open_ms=round(open_s * 1e3, 2),
        term_query_ms_cold=round(term_lats[0] * 1e3, 3),
        term_query_ms_warm=round(float(np.median(term_lats[1:])) * 1e3, 3),
        term_matched=term_n,
        regexp_query_ms_cold=round(re_lats[0] * 1e3, 3),
        regexp_query_ms_cached=round(float(np.median(re_lats[1:])) * 1e3, 3),
        regexp_matched=re_n,
        regexp_materialize_ms=round(mat_s * 1e3, 1),
        regexp_ids_only_ms=round(ids_s * 1e3, 1),
    )


def bench_index_device(series_counts, tmpdir="/tmp/m3tpu-index-device-bench"):
    """Device-vs-host index_resolve sweep (ISSUE 10's flatness claim,
    measured): for each series count build a namespace index with the
    device tier on, seal (admitting the segment into HBM), and report
    p50 resolve latency for a regexp + a conjunction query through the
    device executor vs the SAME index host-forced — plus matched
    docs/sec through the device path. ``index_resolve`` staying flat as
    the series count grows is the success metric; the sweep makes it a
    number instead of an assertion."""
    import shutil

    from m3_tpu.index.device import DeviceIndexStore, IndexDeviceOptions
    from m3_tpu.index.ns_index import NamespaceIndex
    from m3_tpu.index.query import conj, regexp as regexp_q, term as term_q

    HOUR = 3600 * NANOS
    shutil.rmtree(tmpdir, ignore_errors=True)
    queries = [
        ("regexp", regexp_q(b"name", b"metric_1[0-9]")),
        ("conj", conj(term_q(b"dc", b"dc1"), regexp_q(b"name", b"metric_.*"))),
    ]
    sweep = []
    last_docs_per_s = 0.0
    for n_series in series_counts:
        store = DeviceIndexStore(IndexDeviceOptions(max_bytes=1 << 30))
        ix = NamespaceIndex(block_size_nanos=HOUR, device_store=store)
        ix.write_batch(
            [
                (
                    f"s{i}".encode(),
                    (
                        (b"dc", b"dc%d" % (i % 4)),
                        (b"host", b"h%d" % (i % 50021)),
                        (b"name", b"metric_%d" % (i % 100)),
                    ),
                    T0,
                )
                for i in range(n_series)
            ]
        )
        ix.seal_before(T0 + 2 * HOUR)
        assert store.stats()["admissions"] == 1, store.stats()
        row = {"series": n_series}
        for qname, q in queries:
            # ids() materializes doc ids only — the executor's own cost,
            # not per-doc tag decode
            def run(force_host, iters=7):
                lats = []
                matched = 0
                for _ in range(iters):
                    t0 = time.perf_counter()
                    r = ix.query(q, T0 - HOUR, T0 + HOUR, force_host=force_host)
                    matched = len(r.docs.ids())
                    lats.append(time.perf_counter() - t0)
                return float(np.median(lats)), matched

            run(False, iters=2)  # device warmup: jit compiles excluded
            dev_p50, matched = run(False)
            host_p50, matched_h = run(True)
            assert matched == matched_h, (qname, matched, matched_h)
            row[f"{qname}_device_p50_ms"] = round(dev_p50 * 1e3, 3)
            row[f"{qname}_host_p50_ms"] = round(host_p50 * 1e3, 3)
            row[f"{qname}_matched"] = matched
            if qname == "regexp":
                last_docs_per_s = matched / max(dev_p50, 1e-9)
                row["matched_docs_per_s"] = round(last_docs_per_s)
        sweep.append(row)
        assert store.stats()["errors"] == 0
    # growth factors across the sweep, normalized to the series growth:
    # 1.0 = perfectly linear, < host = the device path flattens the curve
    # (CPU runs are sanity only — the kernels are built for TPU vector
    # units, where the host python/numpy walk is the one that can't keep
    # up; see BASELINE.md's platform note)
    growth = {}
    if len(sweep) >= 2:
        s_growth = sweep[-1]["series"] / sweep[0]["series"]
        for qname, _ in queries:
            for side in ("device", "host"):
                k = f"{qname}_{side}_p50_ms"
                growth[f"{qname}_{side}_growth"] = round(
                    (sweep[-1][k] / max(sweep[0][k], 1e-9)) / s_growth, 3
                )
    return _rec(
        "index_device_resolve",
        last_docs_per_s,
        "matched_docs/s",
        sweep=sweep,
        **growth,
    )


def bench_soak():
    """Composed production-soak SLO gate (tools/check_soak.py): a seeded
    multi-process RF=3 cluster + cluster-mode coordinator + aggregator HA
    pair under overlapping acts (diurnal load, write storm, tenant flood,
    node add+drain, aggregator leader SIGKILL, backfill burst, seeded
    stragglers), with the SLO engine as the verdict. The headline is the
    availability error budget still standing after ~90s of that. NOT in
    the default config set — it spawns a fleet and owns the box while it
    runs; invoke it deliberately (``--configs soak``, the CI gate)."""
    import os
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools", "check_soak.py"
    )
    proc = subprocess.run(
        [sys.executable, script, "--json"],
        capture_output=True, text=True, timeout=900,
    )
    summary = None
    for line in proc.stdout.splitlines():
        if line.startswith("{"):
            summary = json.loads(line)
    assert summary is not None, (proc.stdout[-2000:], proc.stderr[-2000:])
    assert proc.returncode == 0 and not summary.get("failures"), summary
    return _rec(
        "soak_slo_gate",
        summary["availability_budget_remaining"],
        "availability budget remaining",
        elapsed_secs=summary["elapsed_secs"],
        total_ops=summary["total_ops"],
        client_errors=summary["client_errors"],
        sheds=summary["sheds"],
        availability_sli=summary["availability_sli"],
        latency_sli=summary["latency_sli"],
        durability_probes=summary["durability_probes"],
        freshness_probes=summary["freshness_probes"],
        rollup_windows=summary["rollup_windows"],
    )


def main() -> None:
    import jax

    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--configs",
        default="1,2,3,4,5,mixed,scan,index,compression,tenants,pipeline,ingest",
    )
    ap.add_argument("--series", type=int, default=0, help="override config-2 series")
    ap.add_argument("--out", default="PERF_r05.json")
    args = ap.parse_args()

    on_tpu = jax.devices()[0].platform == "tpu"
    big = on_tpu
    s2 = args.series or (1048576 if big else 2048)
    s_mixed = 524288 if big else 2048
    s3 = 102400 if big else 4096
    s4 = 10_000_000 if big else 100_000
    s5 = 10_000_000 if big else 20_000  # r05: 10M indexed (VERDICT #4)

    want = set(args.configs.split(","))
    records = []
    if "1" in want:
        records.append(bench_config1())
    if "2" in want:
        records.append(bench_config2(s2, on_tpu))
    if "mixed" in want:
        records.append(bench_mixed(s_mixed, on_tpu))
    if "scan" in want:
        records.append(bench_scan_p50(s2, on_tpu))
    if "3" in want:
        records.append(bench_config3(s3))
    if "4" in want:
        records.append(bench_config4(s4))
    if "5" in want:
        records.append(bench_config5(s5, on_tpu))
    if "index" in want:
        records.append(bench_index(5_000_000 if big else 100_000))
        records.append(
            bench_index_device(
                [65536, 262144, 1048576] if big else [65536, 262144]
            )
        )
    if "compression" in want:
        records.append(bench_compression())
    if "tenants" in want:
        records.append(bench_multitenant())
        records.append(bench_hedging())
    if "pipeline" in want:
        records.append(bench_pipeline(on_tpu=on_tpu))
    ingest_records = None
    if "ingest" in want:
        ingest_records = bench_ingest(on_tpu)
        records.extend(ingest_records)
    soak_record = None
    if "soak" in want:
        soak_record = bench_soak()
        records.append(soak_record)

    # merge into an existing results file: re-running a subset of configs
    # replaces those records and keeps the rest
    merged: dict[str, dict] = {}
    try:
        with open(args.out) as f:
            for r in json.load(f).get("records", []):
                merged[r["metric"]] = r
    except (OSError, ValueError):
        pass
    for r in records:
        merged[r["metric"]] = r
    with open(args.out, "w") as f:
        json.dump(
            {
                "platform": jax.devices()[0].device_kind,
                "records": list(merged.values()),
            },
            f,
            indent=1,
        )
    if soak_record is not None:
        # BENCH_r07: the SLO round's headline — the error budget the
        # fleet kept through the composed soak, with the act mix's vitals
        with open("BENCH_r07.json", "w") as f:
            json.dump(
                {
                    "platform": jax.devices()[0].device_kind,
                    "parsed": soak_record,
                    "records": [soak_record],
                },
                f,
                indent=1,
            )
    if ingest_records is not None:
        # BENCH_r06: the ingest round's headline (write-plane writes/s
        # vs the PROFILE.md 291k/s/core host ceiling) + its satellites
        with open("BENCH_r06.json", "w") as f:
            json.dump(
                {
                    "platform": jax.devices()[0].device_kind,
                    "parsed": ingest_records[0],
                    "records": ingest_records,
                },
                f,
                indent=1,
            )


if __name__ == "__main__":
    main()
