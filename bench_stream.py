"""Sustained streaming benchmark: host→device upload + fused decode at 1M
series (BASELINE config-5 direction: working set larger than one transfer).

Unlike bench.py (device-resident arrays, pure kernel throughput), every
timed iteration re-uploads each packed batch from host memory, so the
number includes the host→device pipeline (parallel/stream.py double
buffering).

CAVEAT for this environment: host→device rides a shared network tunnel
whose effective bandwidth swings ~100x between runs (measured 0.07s to
>10s draining identical 47M-point batches). Treat the figure as a lower
bound; on a real TPU host the pipeline is bounded by PCIe/host DMA
(tens of GB/s) and the same code measures accordingly.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import time

NORTH_STAR = 10e9  # datapoints/sec/chip, same scale as bench.py


def main() -> None:
    import jax

    # the Mosaic compile of the packed kernel is ~2min through the remote
    # compile tunnel; cache it across runs
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("JAX_CACHE_DIR", os.path.expanduser("~/.cache/jax_comp_cache")),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

    from m3_tpu.ops.chunked import build_chunked, tile_chunked
    from m3_tpu.parallel.stream import packed_batches, stream_aggregate
    from m3_tpu.utils.synthetic import synthetic_streams

    n_points = 720
    k = 24
    # NOTE: in this environment host->device rides an axon tunnel measured
    # at ~1.4 GB/s, so the sustained number is transfer-bound; real PCIe /
    # host DMA is ~30x that. 1M series (BENCH_SERIES=1048576) works but
    # takes ~10 GB of host batches and minutes of tunnel time.
    n_series = int(os.environ.get("BENCH_SERIES", 262144))
    batch_series = int(os.environ.get("BENCH_BATCH", 65536))
    platform = jax.devices()[0].platform
    if platform == "cpu":
        # enough batches that the median interval is a real steady-state
        # statistic: with only 2, the single drain interval lands in the
        # pipeline-fill phase and over-reports throughput ~25% (measured)
        n_series = min(n_series, 32768)
        batch_series = min(batch_series, 4096)

    import numpy as np

    base = build_chunked(synthetic_streams(64, n_points, seed=3), k=k)
    n_batches = -(-n_series // batch_series)
    # ONE host-side packed batch, cycled: every iteration is still a full
    # host→device upload + fused decode of batch_series series (the device
    # cannot tell repeated bytes from fresh ones), so cycling measures the
    # identical pipeline while keeping host memory flat — which is what
    # lets this bench run at 10M+ series (n_batches in the hundreds).
    one = next(iter(packed_batches([tile_chunked(base, batch_series)])))
    host = [one] * n_batches

    # Steady-state measurement within ONE pass: the first drain absorbs
    # compile + pipeline fill; per-batch intervals are summarized by their
    # MEDIAN, which is robust to the tunnel's burst variance (repeat
    # whole-pass timing is unusable in this environment: device buffer
    # churn through the axon tunnel stalls later passes in ways real hosts
    # don't).
    marks = stream_aggregate(host, prefetch=2, drain_times=(times := []))
    total_points = int(marks.total_count)
    per_batch = total_points // n_batches
    diffs = np.diff(np.asarray(times))
    if not len(diffs):  # single batch: no steady-state intervals to report
        diffs = np.asarray([float("nan")])
    med = float(np.median(diffs))
    wall = times[-1] - times[0] if len(times) > 1 else float("nan")

    dps = per_batch / med
    # ---- resident side-by-side: the same bytes decoded FROM HBM ----
    # Streamed above re-uploads every batch; here the compressed streams
    # sit in the paged resident pool (m3_tpu/resident/) and each scan is a
    # device page gather + decode — the transfer term drops out entirely.
    resident = {}
    try:
        resident = _resident_side(n_points, platform, k=k)
    except Exception as exc:  # never cost the streamed line
        import sys

        print(f"WARN resident side failed: {exc}", file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "m3tsz_streamed_decode_aggregate_datapoints_per_sec",
                "value": round(dps, 1),
                "unit": "datapoints/s",
                "vs_baseline": round(dps / NORTH_STAR, 6),
                "series": n_series,
                "batches": n_batches,
                "per_batch_s_p10": round(float(np.percentile(diffs, 10)), 4),
                "per_batch_s_p50": round(med, 4),
                "per_batch_s_p90": round(float(np.percentile(diffs, 90)), 4),
                "steady_state_wall_s": round(wall, 2),
                "scan_wall_dps": round(total_points / (wall + med), 1),
                **{
                    ("resident_" + k if not k.startswith("resident") else k): v
                    for k, v in resident.items()
                },
                **(
                    {"resident_vs_streamed": round(resident["dps"] / dps, 3)}
                    if resident.get("dps")
                    else {}
                ),
            }
        )
    )


def _resident_side(n_points: int, platform: str, k: int = 24) -> dict:
    """Warm decode-from-HBM scan over pool-resident synthetic streams.

    EQUAL SETTINGS with the streamed line above: same chunk size ``k``,
    same per-scan series count as one streamed batch, and the SAME packed
    fused kernel — the side planes paged in at admission let the resident
    scan assemble PackedLanes by device gather, so the only difference
    left is assembly-from-HBM vs host-pack + upload. Also reports the
    zero-transfer contract: warm scans move no block bytes host->device
    (upload/streamed counters flat across the timed iterations)."""
    import time as _time

    from m3_tpu.cache.block_cache import BlockKey
    from m3_tpu.resident import ResidentOptions, ResidentPool, resident_scan_totals
    from m3_tpu.utils.synthetic import synthetic_streams

    # Deliberately NOT bench.py's BENCH_RESIDENT_SERIES: sizing one bench
    # must not silently resize the other's recorded metric.
    n_resident = int(
        os.environ.get(
            "BENCH_STREAM_RESIDENT_SERIES", 65536 if platform == "tpu" else 4096
        )
    )
    uniq = synthetic_streams(64, n_points, seed=3)
    pool = ResidentPool(
        ResidentOptions(max_bytes=max(64 << 20, n_resident * 4096 * 4))
    )
    bound = n_points + 8
    t0 = 0
    for start in range(0, n_resident, 4096):
        n = min(4096, n_resident - start)
        pool.admit_block(
            "bench",
            0,
            t0,
            start,  # one synthetic "volume" per admission batch
            [(b"s%07d" % (start + i), uniq[i % len(uniq)], bound) for i in range(n)],
            chunk_k=k,
        )
    keys = [
        BlockKey("bench", 0, b"s%07d" % i, t0, (i // 4096) * 4096)
        for i in range(n_resident)
    ]
    warm = resident_scan_totals(pool, keys)  # compile + warm
    total = int(warm.total_count)
    before = pool.stats()["upload_bytes"]
    # SAME steady-state methodology as the streamed line: an inflight
    # window of 2 scans with a hard scalar-fetch drain per result, timed
    # by drain intervals — dispatch of scan N+1 overlaps compute of scan
    # N exactly as stream_aggregate pipelines its batches.
    import collections

    import numpy as _np

    iters = 6
    inflight: collections.deque = collections.deque()
    times: list[float] = []
    for _ in range(iters):
        inflight.append(resident_scan_totals(pool, keys, device_out=True))
        if len(inflight) > 2:
            _np.asarray(inflight.popleft().total_count)
            times.append(_time.perf_counter())
    while inflight:
        _np.asarray(inflight.popleft().total_count)
        times.append(_time.perf_counter())
    diffs = _np.diff(_np.asarray(times))
    dt = float(_np.median(diffs)) if len(diffs) else float("nan")
    return {
        "dps": round(total / dt, 1),
        "series": n_resident,
        "scan_s": round(dt, 4),
        "pool_occupancy": round(pool.stats()["occupancy"], 6),
        # zero-transfer contract: warm scans admit/upload nothing
        "warm_block_bytes_transferred": pool.stats()["upload_bytes"] - before,
    }


if __name__ == "__main__":
    main()
