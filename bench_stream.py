"""Sustained streaming benchmark: host→device upload + fused decode at 1M
series (BASELINE config-5 direction: working set larger than one transfer).

Unlike bench.py (device-resident arrays, pure kernel throughput), every
timed iteration re-uploads each packed batch from host memory, so the
number includes the host→device pipeline (parallel/stream.py double
buffering).

CAVEAT for this environment: host→device rides a shared network tunnel
whose effective bandwidth swings ~100x between runs (measured 0.07s to
>10s draining identical 47M-point batches). Treat the figure as a lower
bound; on a real TPU host the pipeline is bounded by PCIe/host DMA
(tens of GB/s) and the same code measures accordingly.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import time

NORTH_STAR = 10e9  # datapoints/sec/chip, same scale as bench.py


def main() -> None:
    import jax

    # the Mosaic compile of the packed kernel is ~2min through the remote
    # compile tunnel; cache it across runs
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("JAX_CACHE_DIR", os.path.expanduser("~/.cache/jax_comp_cache")),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

    from m3_tpu.ops.chunked import build_chunked, tile_chunked
    from m3_tpu.parallel.stream import packed_batches, stream_aggregate
    from m3_tpu.utils.synthetic import synthetic_streams

    n_points = 720
    k = 24
    # NOTE: in this environment host->device rides an axon tunnel measured
    # at ~1.4 GB/s, so the sustained number is transfer-bound; real PCIe /
    # host DMA is ~30x that. 1M series (BENCH_SERIES=1048576) works but
    # takes ~10 GB of host batches and minutes of tunnel time.
    n_series = int(os.environ.get("BENCH_SERIES", 262144))
    batch_series = int(os.environ.get("BENCH_BATCH", 65536))
    platform = jax.devices()[0].platform
    if platform == "cpu":
        n_series = min(n_series, 8192)
        batch_series = min(batch_series, 4096)

    import numpy as np

    base = build_chunked(synthetic_streams(64, n_points, seed=3), k=k)
    n_batches = -(-n_series // batch_series)
    # ONE host-side packed batch, cycled: every iteration is still a full
    # host→device upload + fused decode of batch_series series (the device
    # cannot tell repeated bytes from fresh ones), so cycling measures the
    # identical pipeline while keeping host memory flat — which is what
    # lets this bench run at 10M+ series (n_batches in the hundreds).
    one = next(iter(packed_batches([tile_chunked(base, batch_series)])))
    host = [one] * n_batches

    # Steady-state measurement within ONE pass: the first drain absorbs
    # compile + pipeline fill; per-batch intervals are summarized by their
    # MEDIAN, which is robust to the tunnel's burst variance (repeat
    # whole-pass timing is unusable in this environment: device buffer
    # churn through the axon tunnel stalls later passes in ways real hosts
    # don't).
    marks = stream_aggregate(host, prefetch=2, drain_times=(times := []))
    total_points = int(marks.total_count)
    per_batch = total_points // n_batches
    diffs = np.diff(np.asarray(times))
    if not len(diffs):  # single batch: no steady-state intervals to report
        diffs = np.asarray([float("nan")])
    med = float(np.median(diffs))
    wall = times[-1] - times[0] if len(times) > 1 else float("nan")

    dps = per_batch / med
    print(
        json.dumps(
            {
                "metric": "m3tsz_streamed_decode_aggregate_datapoints_per_sec",
                "value": round(dps, 1),
                "unit": "datapoints/s",
                "vs_baseline": round(dps / NORTH_STAR, 6),
                "series": n_series,
                "batches": n_batches,
                "per_batch_s_p10": round(float(np.percentile(diffs, 10)), 4),
                "per_batch_s_p50": round(med, 4),
                "per_batch_s_p90": round(float(np.percentile(diffs, 90)), 4),
                "steady_state_wall_s": round(wall, 2),
                "scan_wall_dps": round(total_points / (wall + med), 1),
            }
        )
    )


if __name__ == "__main__":
    main()
