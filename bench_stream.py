"""Sustained streaming benchmark: host→device upload + fused decode at 1M
series (BASELINE config-5 direction: working set larger than one transfer).

Unlike bench.py (device-resident arrays, pure kernel throughput), every
timed iteration re-uploads each packed batch from host memory, so the
number includes the host→device pipeline (parallel/stream.py double
buffering).

CAVEAT for this environment: host→device rides a shared network tunnel
whose effective bandwidth swings ~100x between runs (measured 0.07s to
>10s draining identical 47M-point batches). Treat the figure as a lower
bound; on a real TPU host the pipeline is bounded by PCIe/host DMA
(tens of GB/s) and the same code measures accordingly.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import time

NORTH_STAR = 10e9  # datapoints/sec/chip, same scale as bench.py


def main() -> None:
    import jax

    # the Mosaic compile of the packed kernel is ~2min through the remote
    # compile tunnel; cache it across runs
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("JAX_CACHE_DIR", os.path.expanduser("~/.cache/jax_comp_cache")),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

    from m3_tpu.ops.chunked import build_chunked, tile_chunked
    from m3_tpu.parallel.stream import packed_batches, stream_aggregate
    from m3_tpu.utils.synthetic import synthetic_streams

    n_points = 720
    k = 24
    # NOTE: in this environment host->device rides an axon tunnel measured
    # at ~1.4 GB/s, so the sustained number is transfer-bound; real PCIe /
    # host DMA is ~30x that. 1M series (BENCH_SERIES=1048576) works but
    # takes ~10 GB of host batches and minutes of tunnel time.
    n_series = int(os.environ.get("BENCH_SERIES", 262144))
    batch_series = int(os.environ.get("BENCH_BATCH", 65536))
    platform = jax.devices()[0].platform
    if platform == "cpu":
        n_series = min(n_series, 8192)
        batch_series = min(batch_series, 4096)

    base = build_chunked(synthetic_streams(64, n_points, seed=3), k=k)
    n_batches = -(-n_series // batch_series)
    host = list(
        packed_batches(tile_chunked(base, batch_series) for _ in range(n_batches))
    )

    # Steady-state measurement within ONE pass: the first drain absorbs
    # compile + pipeline fill; the window from first to last drain covers
    # n_batches - 1 batches of sustained upload+decode. (Repeat whole-pass
    # timing is unusable in this environment: device buffer churn through
    # the axon tunnel stalls later passes in ways real hosts don't.)
    marks = stream_aggregate(host, prefetch=2, drain_times=(times := []))
    total_points = marks.total_count
    per_batch = total_points // n_batches
    dt = (times[-1] - times[0]) / max(n_batches - 1, 1)

    dps = per_batch / dt
    print(
        json.dumps(
            {
                "metric": "m3tsz_streamed_decode_aggregate_datapoints_per_sec",
                "value": round(dps, 1),
                "unit": "datapoints/s",
                "vs_baseline": round(dps / NORTH_STAR, 6),
                "series": n_series,
                "batches": n_batches,
            }
        )
    )


if __name__ == "__main__":
    main()
